"""DistributedCoreWorker: the per-process runtime core.

Analogue of the reference core worker (ref: src/ray/core_worker/
core_worker.h:291 — task submission, ownership/refcount, memory store,
actor transport; direct task push after lease,
transport/direct_task_transport.h:75). Embedded in the driver and in every
worker process.

Data path: every put/task-return lands in the executing node's shm store and
its location is registered in the GCS object directory; small payloads also
ride inline in task replies as a read shortcut. get() resolves
local-store → inline-cache → remote pull (chunked stream from the holding
node's daemon, ref: object_manager.h:117 pull/push in 5 MiB chunks).
"""
from __future__ import annotations

import asyncio
import atexit
import logging
import os
import threading
import time
import uuid
from collections import defaultdict, deque
from concurrent.futures import CancelledError, Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu import exceptions as rexc
from ray_tpu.core import serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef, install_refcounter, uninstall_refcounter
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.core.task_spec import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
    TaskOptions,
)
from ray_tpu.core.distributed import protocol
from ray_tpu.core.distributed.rpc import (
    AsyncRpcClient,
    EventLoopThread,
    RpcError,
    RpcServer,
    SyncRpcClient,
)
from ray_tpu.core.distributed.wire import Raw

logger = logging.getLogger(__name__)

ACTOR_STATES_TRANSIENT = ("PENDING_CREATION", "RESTARTING")


# Byte-exact serialized None (the serializer is deterministic for None):
# lets the hot get() path recognize a None reply without deserializing.
_NONE_PAYLOAD = serialization.dumps(None)

# One shared condition for every _LightFuture: a per-future
# threading.Condition (an RLock + waiter deque) was a measurable slice of
# actor-call submission at >10k calls/s on a single-core host. Waiters are
# rare relative to futures (get() blocks on at most a handful at a time),
# so notify_all on the shared condition wakes few threads.
_lf_cond = threading.Condition(threading.Lock())

_LF_PENDING = 0
_LF_DONE = 1
_LF_CANCELLED = 2
_LF_ERROR = 3


class _LightFuture:
    """Minimal concurrent.futures.Future replacement for the task/actor
    submission waiter: supports exactly the subset the submit/get paths
    use (done/cancel/set_result/set_exception/result/add_done_callback),
    value is always None — results travel via the inline cache / store,
    the future only signals completion."""

    __slots__ = ("_state", "_exc", "_cbs", "stream_state", "__weakref__")

    def __init__(self):
        self._state = _LF_PENDING
        self._exc = None
        self._cbs = None

    def done(self) -> bool:
        return self._state != _LF_PENDING

    def cancelled(self) -> bool:
        return self._state == _LF_CANCELLED

    def _finish(self, state: int, exc=None) -> bool:
        with _lf_cond:
            if self._state != _LF_PENDING:
                return False
            self._exc = exc
            self._state = state
            _lf_cond.notify_all()
            cbs, self._cbs = self._cbs, None
        if cbs:
            for cb in cbs:
                try:
                    cb(self)
                except Exception:  # noqa: BLE001
                    logger.exception("future callback failed")
        return True

    def set_result(self, _value=None) -> None:
        self._finish(_LF_DONE)

    def set_exception(self, exc) -> None:
        self._finish(_LF_ERROR, exc)

    def cancel(self) -> bool:
        return self._finish(_LF_CANCELLED)

    def exception(self, timeout=None):
        self.result(timeout)
        return self._exc

    def add_done_callback(self, cb) -> None:
        with _lf_cond:
            if self._state == _LF_PENDING:
                if self._cbs is None:
                    self._cbs = [cb]
                else:
                    self._cbs.append(cb)
                return
        try:
            cb(self)
        except Exception:  # noqa: BLE001
            logger.exception("future callback failed")

    def result(self, timeout=None):
        if self._state == _LF_PENDING:
            with _lf_cond:
                if timeout is None:
                    while self._state == _LF_PENDING:
                        _lf_cond.wait()
                else:
                    deadline = time.monotonic() + timeout
                    while self._state == _LF_PENDING:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise FutureTimeoutError()
                        _lf_cond.wait(remaining)
        if self._state == _LF_CANCELLED:
            raise CancelledError()
        if self._state == _LF_ERROR:
            raise self._exc
        return None


class _TaskLane:
    """Tasks with identical (demand, scheduling) share leased workers.

    The reference's direct task submitter holds a granted lease and runs
    further queued tasks of the same shape on it instead of going back to
    the raylet per task (ref: direct_task_transport.h:75 — worker lease
    reuse). Here a lane additionally BATCHES queued specs into one
    push_tasks RPC per worker round, amortizing python-grpc's ~0.5 ms
    per-unary cost. Leases are held `IDLE_HOLD_S` after the queue drains,
    then returned.
    """

    # Idle leases block OTHER lanes' parked waiters (the daemon can't
    # reclaim a held lease), so the hold must only bridge a tight
    # submit-get loop's gap (~1ms lease RT), not a human pause: 200ms
    # serialized 4 contending submitters into 300ms turns each.
    IDLE_HOLD_S = 0.02
    MAX_LEASES = 32
    # Batch size balances RPC amortization (16x fewer unaries) against
    # failure blast radius (a dying worker fails one whole batch) AND
    # placement spread: one pursuer grabbing a 64-deep queue of 200ms
    # tasks serializes 13s of work on one worker while other nodes sit
    # idle. The cap adapts to the lane's observed per-task duration
    # (_batch_cap): micro-tasks batch at 64 (every RPC is pure overhead
    # on a single-core host), long tasks go 1-2 per batch so surplus
    # queue depth spawns more pursuers → more leases → spillback
    # spreads them across nodes (the reference schedules per-task and
    # gets spread for free; lease-reuse batching must buy it back).
    BATCH = 64
    # Before any duration sample exists: small, so a burst of unknown
    # (possibly long) tasks doesn't serialize 8-deep on one worker
    # while other nodes idle; one observed batch later the cap adapts.
    FIRST_BATCH = 2
    # Lease time-slice: return the lease after this many batches even if
    # work remains (re-request immediately). The daemon can't reclaim a
    # held lease, so a lane that drains its whole queue on one lease
    # starves every other submitter's parked waiters; FIFO re-grants at
    # slice boundaries round-robin contending lanes at ~1ms re-lease
    # cost per slice (<1% of a slice's work).
    BATCHES_PER_LEASE = 4
    # Connection-level batch failures re-queue the affected specs (cheap,
    # spread over fresh batches) up to this many times per spec before
    # surfacing the failure.
    MAX_BATCH_RETRIES = 20

    def __init__(self, core: "DistributedCoreWorker", demand, sched):
        self.core = core
        self.demand = demand
        self.sched = sched
        self.queue: deque = deque()
        self.wakeup = asyncio.Event()
        # Number of _pursue coroutines alive; each holds at most one lease.
        self.pursuers = 0
        # EMA of seconds per task on this lane (None until first batch).
        self._ema_task_s: Optional[float] = None

    def _observe_batch(self, n: int, dt: float) -> None:
        per = dt / max(1, n)
        ema = self._ema_task_s
        self._ema_task_s = per if ema is None else 0.7 * ema + 0.3 * per

    def _batch_cap(self) -> int:
        ema = self._ema_task_s
        if ema is None:
            return self.FIRST_BATCH
        if ema < 0.005:
            return self.BATCH
        if ema < 0.05:
            return 8
        if ema < 0.5:
            return 2
        return 1

    async def submit(self, spec: dict) -> dict:
        fut = asyncio.get_running_loop().create_future()
        self.queue.append((spec, fut))
        self.wakeup.set()
        self._maybe_scale()
        return await fut

    def _maybe_scale(self) -> None:
        while self.pursuers < min(len(self.queue), self.MAX_LEASES):
            self.pursuers += 1
            asyncio.ensure_future(self._pursue())

    def _fail_queued(self, e: BaseException) -> None:
        err = e if isinstance(e, Exception) else RuntimeError(repr(e))
        while self.queue:
            spec, fut = self.queue.popleft()
            self.core._record_driver_failure(spec, err)
            if not fut.done():
                fut.set_exception(err)

    async def _pursue(self) -> None:
        """Acquire a lease, run queued tasks on it, repeat while work
        remains. Transient lease failures (RPC deadline while the daemon
        queues us behind busy resources, daemon restarts) back off and
        retry; only a definitive scheduler refusal fails the queue."""
        failures = 0
        cancelled = False
        try:
            while self.queue:
                try:
                    daemon, grant = await self._lease_with_spillback()
                except rexc.RayTpuError as e:
                    self._fail_queued(e)
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 transient
                    failures += 1
                    if failures > 50:
                        self._fail_queued(e)
                        return
                    await asyncio.sleep(min(0.2 * failures, 2.0))
                    continue
                failures = 0
                try:
                    await self._run_worker(daemon, grant)
                finally:
                    try:
                        await daemon.call(
                            "NodeDaemon", "return_lease",
                            lease_id=grant["lease_id"], timeout=10)
                    except asyncio.CancelledError:
                        cancelled = True
                        raise
                    except Exception:  # noqa: BLE001
                        pass
        except asyncio.CancelledError:
            # Event-loop shutdown: cancel waiters instead of spinning the
            # retry loop on a dead control plane, and do NOT respawn a
            # replacement pursuer (it would outlive the cancel sweep and
            # die as a destroyed-pending task at interpreter exit).
            cancelled = True
            for _, fut in self.queue:
                if not fut.done():
                    fut.cancel()
            self.queue.clear()
            raise
        finally:
            self.pursuers -= 1
            if not cancelled:
                self._maybe_scale()

    async def _lease_with_spillback(self):
        cfg = get_config()
        sched = self.sched
        daemon_addr = self.core.daemon_address
        parked = False
        for _ in range(16):  # bounded spillback hops
            daemon = await self.core._aclient(daemon_addr)
            grant = await daemon.call(
                "NodeDaemon", "request_lease", demand=self.demand,
                strategy=sched["strategy"], affinity=sched["affinity"],
                soft=sched["soft"], placement=sched["placement"],
                runtime_env=sched.get("runtime_env"),
                job_id=self.core.job_id, parked=parked,
                timeout=cfg.worker_lease_timeout_ms / 1000)
            if grant.get("spill_to"):
                daemon_addr = grant["spill_to"]
                # A "park" spill is terminal: the target queues us until
                # capacity frees instead of re-spilling on ITS stale
                # view (stops spread-spill ping-pong across busy nodes).
                parked = bool(grant.get("park"))
                continue
            if not grant.get("granted"):
                if grant.get("transient"):
                    # Worker-start hiccup: retryable, not a scheduler
                    # refusal — surface as a transient transport error.
                    raise RpcError(grant.get("error", "transient lease "
                                                      "failure"))
                raise rexc.RayTpuError(
                    grant.get("error", "lease not granted"))
            return daemon, grant
        raise rexc.RayTpuError("too many spillback hops")

    async def _run_worker(self, daemon, grant) -> None:
        worker = await self.core._aclient(grant["worker_address"])
        batches_run = 0
        while True:
            if batches_run >= self.BATCHES_PER_LEASE and self.queue:
                return  # time-slice over: re-lease so other lanes rotate
            batch = []
            cap = self._batch_cap()
            while self.queue and len(batch) < cap:
                spec, fut = self.queue.popleft()
                if spec["task_id"] in self.core._cancelled_tasks:
                    # Cancelled while queued: never push (ref:
                    # CancelTask on unleased tasks). Consuming the
                    # tombstone bounds the set to in-flight cancels.
                    self.core._cancelled_tasks.pop(spec["task_id"], None)
                    if not fut.done():
                        fut.set_result({
                            "results": [],
                            "error": rexc.TaskCancelledError(
                                spec["options"].get("name", "task"))})
                    continue
                batch.append((spec, fut))
            if not batch:
                # Hold the lease briefly: a follow-up burst reuses the
                # worker without another raylet round-trip.
                self.wakeup.clear()
                try:
                    await asyncio.wait_for(self.wakeup.wait(),
                                           self.IDLE_HOLD_S)
                    continue
                except (TimeoutError, asyncio.TimeoutError):
                    return
            for s, _ in batch:
                self.core._task_locations[s["task_id"]] = \
                    grant["worker_address"]
                # LEASED stamp: this attempt is bound to a granted
                # worker; the executor folds it into the attempt's
                # history record (see _stamp_submit).
                s["lease_ts"] = time.time()
            # Per-task STREAMED replies: the batch executes sequentially
            # on one lease, but each task's reply lands as soon as IT
            # finishes — a quick task's waiter is never gated on a slow
            # batchmate. (Pre-owner-serving this visibility came from
            # the executing worker's eager store write + directory
            # registration; with owner-served results the reply IS the
            # visibility.)
            push_t0 = time.monotonic()
            answered = [False] * len(batch)
            requeued = False
            try:
                async for chunk in worker.stream(
                        "Worker", "push_tasks_stream",
                        specs=[s for s, _ in batch]):
                    for i, reply in chunk:
                        spec, fut = batch[i]
                        answered[i] = True
                        self.core._task_locations.pop(spec["task_id"],
                                                      None)
                        if reply.get("requeue"):
                            # Worker retiring (max_calls): the spec
                            # never ran — requeue WITHOUT charging its
                            # retry budget, bounded like connection
                            # retries.
                            n = spec.get("_lane_retries", 0) + 1
                            spec["_lane_retries"] = n
                            if n > self.MAX_BATCH_RETRIES:
                                if not fut.done():
                                    fut.set_result({
                                        "results": [],
                                        "error": rexc.WorkerCrashedError(
                                            "worker kept retiring under "
                                            "max_calls pressure")})
                            else:
                                self.queue.append((spec, fut))
                                requeued = True
                            continue
                        if not fut.done():
                            fut.set_result(reply)
                # A stream that ENDED OK must have answered every spec;
                # requeue any gap defensively rather than stranding its
                # future forever.
                for (spec, fut), done in zip(batch, answered):
                    if done or fut.done():
                        continue
                    self.core._task_locations.pop(spec["task_id"], None)
                    n = spec.get("_lane_retries", 0) + 1
                    spec["_lane_retries"] = n
                    if n > self.MAX_BATCH_RETRIES:
                        fut.set_exception(rexc.WorkerCrashedError(
                            "batch stream ended without this task's "
                            "reply"))
                    else:
                        self.queue.append((spec, fut))
                        requeued = True
            except asyncio.CancelledError:
                # Event-loop shutdown, not a worker death: cancel the
                # unanswered remainder instead of re-queueing forever.
                for (spec, fut), done in zip(batch, answered):
                    if not done:
                        self.core._task_locations.pop(spec["task_id"],
                                                      None)
                        if not fut.done():
                            fut.cancel()
                raise
            except Exception as e:  # noqa: BLE001
                # Worker likely died mid-batch: re-queue the UNANSWERED
                # specs (fresh leases redistribute them) instead of
                # charging each a full retry attempt; answered ones
                # already completed. Locations pop per-spec BEFORE the
                # requeue (a blanket pop afterwards would clobber the
                # fresh location another pursuer may already have set
                # for a re-pushed spec, breaking cancel routing).
                err = e
                for (spec, fut), done in zip(batch, answered):
                    if done:
                        continue
                    self.core._task_locations.pop(spec["task_id"], None)
                    n = spec.get("_lane_retries", 0) + 1
                    spec["_lane_retries"] = n
                    if n > self.MAX_BATCH_RETRIES:
                        if not fut.done():
                            fut.set_exception(err)
                    else:
                        self.queue.append((spec, fut))
                self.wakeup.set()
                self._maybe_scale()
                return  # drop this lease; the worker may be gone
            self._observe_batch(len(batch), time.monotonic() - push_t0)
            if self.queue:
                # Slow tasks shrink the cap AFTER the first batch; give
                # the surplus queue fresh pursuers now (submit-time
                # scaling already happened at the old, larger cap).
                self._maybe_scale()
            batches_run += 1
            if requeued:
                self.wakeup.set()
                self._maybe_scale()
                # Span the retiring worker's exit window so the re-lease
                # grants a FRESH worker instead of looping on this one.
                await asyncio.sleep(0.3)
                return  # drop this lease


class _PinnedLane:
    """A warm, pinned lease for one repeated task signature.

    After `task_lane_min_calls` submissions of the same (function,
    resources, runtime-env) signature, the driver leases a worker once,
    PINS the lease (the daemon releases its resources back to the pool —
    actor semantics — but keeps the worker bound and un-reapable) and
    opens a lane on the worker: the fn_key/name/job_id template travels
    once, and every subsequent call is a compact delta frame (task id +
    raw arg blob + counters, wire codec 2) straight into the pinned
    worker's executor queue. No per-call TaskSpec pickle, no
    GCS/scheduler/daemon visit, no lease round-trip.

    Spillback is transparent: a full in-flight window, a lost lease, a
    retiring or dying worker all route the call back to the ordinary
    `_TaskLane` lease/scheduler path (the memoized-results check on the
    worker keeps a retried call from re-running a body whose results
    already landed). Idle lanes release their worker after
    `task_lane_idle_s` so the pool can reap it.
    """

    def __init__(self, core: "DistributedCoreWorker", key, demand, sched,
                 fn_key: bytes, name: str, exclusive: bool = False):
        self.core = core
        self.key = key
        self.demand = demand
        self.sched = sched
        self.fn_key = fn_key
        self.name = name
        self.exclusive = exclusive   # compiled-DAG stage lane: not shared
        self.lane_id = uuid.uuid4().hex
        self.state = "opening"        # opening -> ready -> closed
        self.inflight = 0
        self.last_used = time.monotonic()
        self.worker_address: Optional[str] = None
        self.node_id: Optional[str] = None
        self.daemon_address: Optional[str] = None
        self.lease_id: Optional[str] = None
        self._client: Optional[AsyncRpcClient] = None
        core._lane_stat("opened")
        self._open_task: Optional[asyncio.Future] = \
            asyncio.ensure_future(self._open())

    async def _open(self) -> None:
        """Lease + pin + lane_open. Runs once; callers await it."""
        helper = _TaskLane(self.core, self.demand, self.sched)
        try:
            daemon, grant = await helper._lease_with_spillback()
            self.lease_id = grant["lease_id"]
            self.daemon_address = grant.get("daemon_address")
            self.node_id = grant.get("node_id")
            self.worker_address = grant["worker_address"]
            pin = await daemon.call("NodeDaemon", "pin_lease",
                                    lease_id=self.lease_id, timeout=10)
            if not pin.get("ok"):
                raise RpcError(f"pin_lease: {pin.get('error')}")
            # Dedicated connection: the lane's frames never queue behind
            # the shared client's control traffic, and teardown closes it.
            self._client = AsyncRpcClient(self.worker_address)
            opened = await self._client.call(
                "Worker", "lane_open", lane_id=self.lane_id,
                fn_key=self.fn_key, name=self.name,
                job_id=self.core.job_id,
                submit_ctx=getattr(self.core, "_submit_identity", None),
                timeout=60)
            if not opened.get("ok"):
                raise RpcError(f"lane_open: {opened.get('error')}")
            self.state = "ready"
        except BaseException:
            self.close()
            raise

    def try_submit(self, spec: dict, rfut: asyncio.Future) -> bool:
        """Fast-path admission; False => caller spills to the slow path."""
        if self.state == "closed" \
                or self.inflight >= get_config().task_lane_max_inflight:
            return False
        self.inflight += 1
        self.last_used = time.monotonic()
        asyncio.ensure_future(self._call(spec, rfut))
        return True

    async def _call(self, spec: dict, rfut: asyncio.Future) -> None:
        try:
            reply = await self._execute(spec)
        except asyncio.CancelledError:
            if not rfut.done():
                rfut.cancel()
            raise
        except BaseException as e:  # noqa: BLE001 — spill via on_done
            if not rfut.done():
                rfut.set_exception(e)
        else:
            if not rfut.done():
                rfut.set_result(reply)
        finally:
            self.inflight -= 1
            self.last_used = time.monotonic()

    async def _execute(self, spec: dict) -> dict:
        if self._open_task is not None:
            await asyncio.shield(self._open_task)
            self._open_task = None
        if self.state != "ready":
            raise RpcError("lane closed")
        if spec["task_id"] in self.core._cancelled_tasks:
            self.core._cancelled_tasks.pop(spec["task_id"], None)
            return {"results": [], "error": rexc.TaskCancelledError(
                spec["options"].get("name", "task"))}
        self.core._task_locations[spec["task_id"]] = self.worker_address
        spec["lease_ts"] = time.time()
        try:
            reply = await self._client.call(
                "Worker", "lane_execute", lane_id=self.lane_id,
                task_id=spec["task_id"],
                num_returns=spec["num_returns"],
                attempt=spec.get("attempt", 0),
                lane_retries=spec.get("_lane_retries", 0),
                submit_ts=spec.get("submit_ts"),
                lease_ts=spec["lease_ts"],
                args_blob=Raw(spec["args_blob"]), timeout=None)
        except asyncio.CancelledError:
            self.core._task_locations.pop(spec["task_id"], None)
            raise
        except Exception as e:  # noqa: BLE001 — worker likely died
            self.core._task_locations.pop(spec["task_id"], None)
            spec["_lane_retries"] = spec.get("_lane_retries", 0) + 1
            self.close()
            raise RpcError(f"lane transport failure: {e!r}")
        self.core._task_locations.pop(spec["task_id"], None)
        if reply.get("requeue"):
            # Worker retiring / lane evaporated: the call never ran.
            spec["_lane_retries"] = spec.get("_lane_retries", 0) + 1
            self.close()
            raise RpcError("lane worker retiring")
        return reply

    async def apply_async(self, blob: bytes, name: str = "dag_stage"):
        """Long-running lane body (compiled-DAG stage loop): returns the
        in-flight call's coroutine result dict when the loop exits."""
        if self._open_task is not None:
            await asyncio.shield(self._open_task)
            self._open_task = None
        if self.state != "ready":
            raise RpcError("lane closed")
        return await self._client.call("Worker", "lane_apply",
                                       blob=Raw(blob), name=name,
                                       timeout=None)

    def close(self, reason: str = "") -> None:
        """Idempotent teardown: unregister, close the worker lane,
        return (unpin) the lease, drop the dedicated connection."""
        if self.state == "closed":
            return
        self.state = "closed"
        if not self.exclusive \
                and self.core._pinned_lanes.get(self.key) is self:
            del self.core._pinned_lanes[self.key]
        self.core._lane_stat("closed")
        asyncio.ensure_future(self._close_async())

    async def _close_async(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            try:
                await client.call("Worker", "lane_close",
                                  lane_id=self.lane_id, timeout=5)
            except Exception:  # noqa: BLE001 — worker may be gone
                pass
            try:
                await client.close()
            except Exception:  # noqa: BLE001
                pass
        if self.daemon_address and self.lease_id:
            # Unpin: a dead worker's lease was already auto-returned by
            # the daemon's monitor; the double return is a no-op.
            try:
                daemon = await self.core._aclient(self.daemon_address)
                await daemon.call("NodeDaemon", "return_lease",
                                  lease_id=self.lease_id, timeout=10)
            except Exception:  # noqa: BLE001
                pass


class OwnerService:
    """Serves this process's owned small objects to other processes.

    The TPU-native analogue of the reference's owner-based in-process
    memory store served over CoreWorkerService.GetObjectStatus (ref:
    src/ray/core_worker/core_worker.cc HandleGetObjectStatus returning
    in-band small values; memory_store.cc): small task returns live in
    the OWNER's inline cache — never eagerly written to the node store —
    and any process holding a ref (refs pickle with their owner address)
    fetches them from the owner on a directory miss. Owner death loses
    the object, exactly as in the reference."""

    def __init__(self, core: "DistributedCoreWorker"):
        self.core = core

    def get_object(self, object_id: bytes) -> dict:
        oid = ObjectID(object_id)
        payload = self.core._inline_cache.get(oid)
        if payload is None:
            buf = self.core.store.get_buffer(oid)
            if buf is not None:
                payload = bytes(buf.view)
        return {"payload": payload,
                "pending": payload is None
                and oid in self.core._pending_objects}

    def borrow_update(self, events) -> dict:
        """Batched borrow protocol deltas from a borrower: see
        DistributedCoreWorker._ref_serialized."""
        self.core.apply_borrow_update(events)
        return {"ok": True}


class DistributedCoreWorker:
    def __init__(
        self,
        *,
        gcs_address: str,
        node_id: str,
        daemon_address: str,
        store_dir: str,
        job_id: str,
        is_driver: bool,
        worker_address: str = "",
        loop_thread: Optional[EventLoopThread] = None,
        log_to_driver: bool = True,
    ):
        self.gcs_address = gcs_address
        self.node_id = node_id
        self.node_id_hex = node_id
        self.daemon_address = daemon_address
        self.job_id = job_id
        self.is_driver = is_driver

        # grpc.aio binds its poller to one event loop per process — every
        # grpc object (server + clients) must live on this single loop.
        self.loop_thread = loop_thread or EventLoopThread(
            name="core-worker-rpc")
        self._owner_server = None
        if worker_address:
            self.address = worker_address
        else:
            # Drivers serve their owned small objects too (workers
            # register OwnerService on their existing server): every
            # owner is addressable, so inline results need no eager
            # node-store write. See OwnerService.
            self._owner_server = RpcServer("127.0.0.1", 0)
            self._owner_server.add_service("Owner", OwnerService(self))
            self.loop_thread.run(self._owner_server.start())
            self.address = self._owner_server.address
        self._owner_clients: Dict[str, SyncRpcClient] = {}
        # GCS load attribution: drivers and workers are the "client"
        # component — ad-hoc state reads, KV, object directory calls.
        from ray_tpu.core.distributed.rpc import set_caller_identity

        set_caller_identity(node_id, "client")
        self.gcs = SyncRpcClient(gcs_address, self.loop_thread)
        from ray_tpu.core.distributed.pull_manager import PullManager
        from ray_tpu.core.distributed.transfer import (
            RawChunkFetcher, make_transfer_metrics)

        # Striped transfer backend: raw-frame chunks fetched from every
        # replica at once land straight in the local store's mmap
        # (recv_into, create-then-fill) — pull_manager.py / transfer.py.
        self._xfer_metrics = make_transfer_metrics(
            {"node_id": node_id[:12], "component": "worker"})
        self._chunk_fetcher = RawChunkFetcher()
        self._pull_manager = PullManager(
            self.loop_thread.loop,
            fetch_chunk=self._chunk_fetcher.fetch,
            open_sink=self._open_pull_sink,
            metrics=self._xfer_metrics)
        self._submit_buffer: deque = deque()
        self._submit_scheduled = False
        # Bounded task-event pipeline (task_events.py): this process's
        # status transitions (drivers: SUBMITTED/LEASED; executors:
        # RUNNING/terminal), opt-in profile events, and tracing spans
        # all coalesce here and flush to the GCS off the hot path.
        from ray_tpu.core.distributed.task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer(
            flush_fn=self._flush_task_events, node_id=node_id,
            pid=os.getpid())
        self._submit_identity = (node_id, os.getpid())
        if get_config().task_events_enabled or get_config().tracing_enabled:
            self.loop_thread.submit(self.task_events.flush_loop())
        if get_config().tracing_enabled:
            # Spans get stamped with this process's node so the timeline
            # places them under the emitting node/worker rows.
            from ray_tpu.util import tracing

            tracing.set_node_context(node_id)
        self.loop_thread.submit(self._borrow_sweep_loop())
        self.daemon = SyncRpcClient(daemon_address, self.loop_thread)
        self.store = ObjectStore(store_dir)

        # ---- ownership / refcounts (owner = this process) ----
        self._lock = threading.RLock()
        self._owned: set = set()                 # ObjectIDs owned here
        self._refcounts: Dict[ObjectID, int] = defaultdict(int)
        self._free_batch: List[bytes] = []
        # ---- borrow protocol state (see _ref_serialized) ----
        # transit: oid -> expiry (serialized-but-unregistered handoffs,
        # one coarse window); borrow: oid -> (count, expiry) registered
        # remote borrowers.
        self._transit_pins: Dict[ObjectID, float] = {}
        self._borrow_pins: Dict[ObjectID, Tuple[int, float]] = {}
        self._borrowed_owner: Dict[ObjectID, str] = {}
        self._deferred_free: set = set()
        self._borrow_outbox: Dict[str, list] = {}
        self._borrow_flush_scheduled = False
        self._borrow_flush_lock: Optional[asyncio.Lock] = None
        self._inline_cache: Dict[ObjectID, bytes] = {}
        # Task ids tombstoned by cancel(): queued entries are swept,
        # running tasks interrupted, retries suppressed. Entries are
        # consumed wherever a cancellation completes; insertion-ordered
        # and bounded (see _tombstone) so a cancel that never meets its
        # task ages out instead of leaking.
        self._cancelled_tasks: Dict[bytes, None] = {}
        # task_id -> None for streaming tasks whose stream is still
        # running (streams register no _pending_objects entries, so
        # cancel() needs its own liveness map to route tombstones).
        self._live_streams: Dict[bytes, None] = {}
        # task_id -> worker address while a lane batch holding it is in
        # flight (routes running-task cancels to the right worker).
        self._task_locations: Dict[bytes, str] = {}
        self._inline_cache_order: deque = deque()

        # ---- pending tasks (futures resolve when reply arrives) ----
        self._pending_objects: Dict[ObjectID, Future] = {}

        # ---- lineage: task specs retained for owned task returns so a
        # lost object can be recomputed by resubmitting its creating task
        # (ref: task_manager.h:208 TaskResubmissionInterface,
        # object_recovery_manager.h:41). Entries are pinned while any
        # downstream lineage entry depends on them (ref: lineage pinning,
        # ray_config_def.h:145) and byte-capped FIFO (:158).
        self._lineage: Dict[ObjectID, dict] = {}
        self._lineage_order: List[ObjectID] = []
        self._lineage_pins: Dict[ObjectID, int] = {}
        self._lineage_bytes = 0
        # Oids whose PINNED lineage was cap-evicted: marked so a later
        # reconstruction attempt fails fast instead of hanging (the
        # reference marks such objects unreconstructable).
        self._lineage_evicted: set = set()

        # ---- function table cache ----
        self._exported_fns: set = set()
        self._fn_cache: Dict[bytes, Any] = {}
        import weakref

        self._fn_key_cache = weakref.WeakKeyDictionary()

        # ---- actor address cache ----
        self._actor_cache: Dict[str, dict] = {}
        self._actor_seq: Dict[str, int] = defaultdict(int)
        # Async channels for the submission pipeline (created lazily ON the
        # loop thread; grpc.aio binds objects to the running loop).
        self._aclients: Dict[str, AsyncRpcClient] = {}
        self._agcs: Optional[AsyncRpcClient] = None
        # Batched directory registration (one RPC per burst, not per
        # result; ref: object location updates ride batched pubsub).
        # Producers append under _loc_lock from any thread; only the
        # first append of a burst pays the loop wake-up — on one-core
        # hosts the self-pipe write alone costs ~ms under GIL contention,
        # so a wake per put() would tax the large-put fast path.
        self._loc_lock = threading.Lock()
        self._loc_batch: List[Tuple[bytes, int]] = []
        self._loc_flushing = False
        self._loc_wake_pending = False
        # Per-worker-address actor push batching.
        self._push_queues: Dict[str, "deque"] = {}
        self._push_flushing: Dict[str, bool] = {}
        # Submissions parked while their actor resolves (FIFO per actor).
        self._actor_pending: Dict[str, "deque"] = {}
        # Lease reuse lanes keyed by (demand, sched, runtime_env).
        self._lanes: Dict[tuple, "_TaskLane"] = {}
        # Pre-leased (pinned) task lanes keyed by (fn_key, demand,
        # sched, runtime_env) + per-signature call counts that decide
        # when a signature is hot enough to pin (task_lane_min_calls).
        self._pinned_lanes: Dict[tuple, "_PinnedLane"] = {}
        self._lane_calls: Dict[tuple, int] = {}
        self._lane_reaper: Optional[asyncio.Future] = None
        self.lane_stats = {"hits": 0, "misses": 0, "spills": 0,
                           "opened": 0, "closed": 0}
        from ray_tpu.util.metrics import Counter

        self._m_lane = Counter(
            "raytpu_task_lane_calls_total",
            "Pre-leased task lane dispatch outcomes",
            tag_keys=("outcome",))
        # Raw runtime_env json -> normalized (pkg:// uploaded) spec.
        self._norm_env_cache: Dict[str, Optional[dict]] = {}
        # Job-level default runtime env (init(runtime_env=...)).
        self.job_runtime_env: Optional[dict] = None

        self._shutdown = False
        install_refcounter(self._ref_added, self._ref_removed,
                           self._ref_serialized)
        # Open the async GCS control connection now, off the critical
        # path: the first put() otherwise pays TCP setup inside its
        # location flush, which contends with the store write for the
        # GIL on small hosts.
        self.loop_thread.submit(self._warm_gcs())
        if is_driver:
            if log_to_driver and get_config().log_to_driver:
                self.loop_thread.submit(self._stream_logs_to_driver())
            atexit.register(self.shutdown)

    async def _stream_logs_to_driver(self) -> None:
        """Relay this job's worker stdout/stderr to the driver, prefixed
        (ref: the log_monitor → GCS pubsub → worker.py print_logs path;
        log records flow from each node's LogMonitor through the GCS
        LogManager's ``logs`` channel). Printing happens on a DEDICATED
        thread: a stalled driver stdout (`python train.py | less`) must
        block log relay only — a print() on the RPC loop would stall
        every RPC in the process."""
        import queue as _queue

        from ray_tpu.core.distributed.log_monitor import format_log_prefix

        printq: "_queue.Queue" = _queue.Queue(maxsize=1000)

        def printer():
            import sys

            while True:
                rec = printq.get()
                if rec is None:
                    return
                prefix = format_log_prefix(rec)
                out = (sys.stderr if rec.get("stream") == "stderr"
                       else sys.stdout)
                for line in rec["lines"]:
                    print(f"{prefix} {line}", file=out, flush=True)

        threading.Thread(target=printer, daemon=True,
                         name="log-printer").start()
        try:
            while not self._shutdown:
                client = AsyncRpcClient(self.gcs_address)
                try:
                    async for rec in client.stream(
                            "Pubsub", "stream_subscribe", channel="logs"):
                        job = rec.get("job_id")
                        # Unattributed lines (worker startup before its
                        # first lease) pass through; other jobs' do not.
                        if job and job != self.job_id:
                            continue
                        try:
                            printq.put_nowait(rec)
                        except _queue.Full:
                            pass  # consumer stalled: drop, don't block
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 GCS blip: reconnect
                    await asyncio.sleep(1.0)
                finally:
                    try:
                        await client.close()
                    except Exception:  # noqa: BLE001
                        pass
        finally:
            try:
                printq.put_nowait(None)
            except _queue.Full:
                pass  # daemon printer thread; lost sentinel is harmless

    # ------------------------------------------------------------------
    # reference counting / distributed GC
    # ------------------------------------------------------------------
    # Borrow protocol (ref: reference_count.h borrower bookkeeping).
    # Serializing an OWNED ref adds a TTL'd transit pin — the object
    # cannot be freed while its ref rides a message to a borrower.
    # Deserializing a borrowed ref queues a batched `borrow_add` to the
    # owner (which converts one transit pin into a tracked borrow);
    # dropping the last local ref queues `borrow_release`. An owned
    # object whose local refcount hits zero while pinned defers its
    # free until the pins clear. Backstops: transit pins expire
    # TRANSIT_PIN_TTL_S after the LAST serialization; registered
    # borrows expire BORROW_TTL_S after their last add/refresh, and
    # live borrowers re-send refreshes every sweep — so a SIGKILLed
    # borrower pins the owner's object for at most one TTL, not
    # forever.
    TRANSIT_PIN_TTL_S = 600.0
    BORROW_TTL_S = 600.0

    def _ref_serialized(self, ref: ObjectRef) -> None:
        if self._shutdown:
            return
        oid = ref.id()
        owner = ref.owner_address
        with self._lock:
            if oid in self._owned:
                self._add_transit_pin_locked(oid)
            elif owner and owner != self.address:
                # Pass-through borrow: tell the owner a new transit is
                # in flight (batched, best-effort; TTL at the owner).
                self._queue_borrow_locked(owner, oid, "transit")

    # Once SOME borrower registered, remaining in-flight handoffs get
    # this grace to register before the transit pin may lapse (borrow
    # pins protect the object from then on). Counting pins per handoff
    # and retiring one per `add` would mis-pair under broadcast (one
    # serialization, N deserializers) and could steal an unrelated
    # handoff's protection — a single coarse expiry cannot.
    TRANSIT_GRACE_S = 60.0

    def _add_transit_pin_locked(self, oid: ObjectID) -> None:
        # ONE coarse expiry — TTL after the LAST serialization — so a
        # hot ref re-sent thousands of times costs O(1) state.
        self._transit_pins[oid] = \
            time.monotonic() + self.TRANSIT_PIN_TTL_S

    def _ref_added(self, ref: ObjectRef) -> None:
        oid = ref.id()
        owner = ref.owner_address
        with self._lock:
            n = self._refcounts[oid]
            self._refcounts[oid] = n + 1
            if (n == 0 and owner and owner != self.address
                    and not self._shutdown):
                self._borrowed_owner[oid] = owner
                self._queue_borrow_locked(owner, oid, "add")

    def _ref_removed(self, ref: ObjectRef) -> None:
        if self._shutdown:
            return
        with self._lock:
            self._decref_locked(ref.id())

    def _decref_locked(self, oid: ObjectID) -> None:
        n = self._refcounts.get(oid)
        if n is None:
            return
        if n <= 1:
            del self._refcounts[oid]
            self._drop_lineage_locked(oid)
            owner = self._borrowed_owner.pop(oid, None)
            if owner is not None:
                self._queue_borrow_locked(owner, oid, "release")
            if oid in self._owned:
                if self._has_pins_locked(oid):
                    # Borrowers (or in-flight handoffs) still reference
                    # this object: free when the pins clear.
                    self._deferred_free.add(oid)
                    return
                self._free_owned_locked(oid)
        else:
            self._refcounts[oid] = n - 1

    def _free_owned_locked(self, oid: ObjectID) -> None:
        self._owned.discard(oid)
        self._deferred_free.discard(oid)
        self._transit_pins.pop(oid, None)
        self._borrow_pins.pop(oid, None)
        self._inline_cache.pop(oid, None)
        self._free_batch.append(oid.binary())
        if len(self._free_batch) >= 100:
            self._flush_frees_locked()

    def _has_pins_locked(self, oid: ObjectID) -> bool:
        now = time.monotonic()
        borrow = self._borrow_pins.get(oid)
        if borrow is not None:
            count, expiry = borrow
            if count > 0 and expiry > now:
                return True
            # Expired: the borrower stopped refreshing (crashed).
            del self._borrow_pins[oid]
        expiry = self._transit_pins.get(oid)
        if expiry is not None:
            if expiry > now:
                return True
            del self._transit_pins[oid]
        return False

    def _queue_borrow_locked(self, owner: str, oid: ObjectID,
                             kind: str) -> None:
        self._borrow_outbox.setdefault(owner, []).append(
            (kind, oid.binary()))
        if not self._borrow_flush_scheduled:
            self._borrow_flush_scheduled = True
            try:
                self.loop_thread.loop.call_soon_threadsafe(
                    self._schedule_borrow_flush)
            except Exception:  # noqa: BLE001 loop shutting down
                self._borrow_flush_scheduled = False

    def _schedule_borrow_flush(self) -> None:
        # Small coalescing delay: a consume loop dropping hundreds of
        # borrowed refs flushes one RPC per owner, not one per ref.
        self.loop_thread.loop.call_later(
            0.1, lambda: asyncio.ensure_future(self._flush_borrows()))

    BORROW_FLUSH_RETRIES = 5

    async def _flush_borrows(self) -> None:
        # Serialized: two concurrent flush bodies could deliver a
        # 'release' (queued during the first flush's failing RPC) ahead
        # of the 'add' it pairs with — the owner would then hold a
        # count-1 borrow pin no borrower ever releases (until TTL).
        if self._borrow_flush_lock is None:
            self._borrow_flush_lock = asyncio.Lock()
        async with self._borrow_flush_lock:
            await self._flush_borrows_serialized()

    async def _flush_borrows_serialized(self) -> None:
        with self._lock:
            outbox, self._borrow_outbox = self._borrow_outbox, {}
            self._borrow_flush_scheduled = False
        for owner, events in outbox.items():
            wire = [(kind, oid_b) for kind, oid_b, *_ in events]
            try:
                client = await self._aclient(owner)
                await client.call(
                    "Owner", "borrow_update", events=wire, timeout=10)
            except Exception:  # noqa: BLE001
                # Transient failure must NOT drop the events — a lost
                # `add` would let a reachable owner free an object a
                # live borrower holds. Re-queue with a retry budget;
                # only a persistently unreachable (dead) owner drops
                # them, and its objects die with it anyway.
                keep = []
                for kind, oid_b, *rest in events:
                    attempts = (rest[0] if rest else 0) + 1
                    if attempts < self.BORROW_FLUSH_RETRIES:
                        keep.append((kind, oid_b, attempts))
                if keep:
                    with self._lock:
                        # PREPEND: a release queued during the retry
                        # window must not be applied before the failed
                        # add it pairs with (events are order-sensitive
                        # per oid).
                        existing = self._borrow_outbox.get(owner, [])
                        self._borrow_outbox[owner] = keep + existing
                        if not self._borrow_flush_scheduled:
                            self._borrow_flush_scheduled = True
                            self.loop_thread.loop.call_later(
                                1.0, lambda: asyncio.ensure_future(
                                    self._flush_borrows()))

    async def _borrow_sweep_loop(self) -> None:
        """Periodic borrow maintenance: refresh this process's live
        borrows at their owners (so their pins don't TTL out under us),
        expire pins whose borrower never registered or crashed, and run
        the deferred frees they were blocking."""
        while not self._shutdown:
            await asyncio.sleep(30.0)
            with self._lock:
                for oid, owner in self._borrowed_owner.items():
                    self._queue_borrow_locked(owner, oid, "refresh")
                for oid in list(self._deferred_free):
                    if (not self._has_pins_locked(oid)
                            and oid not in self._refcounts):
                        self._free_owned_locked(oid)
                self._flush_frees_locked()

    def apply_borrow_update(self, events) -> None:
        """Owner side of the protocol (called via OwnerService)."""
        now = time.monotonic()
        expiry = now + self.BORROW_TTL_S
        with self._lock:
            touched = set()
            for kind, oid_b in events:
                oid = ObjectID(oid_b)
                touched.add(oid)
                if kind == "add":
                    count, _ = self._borrow_pins.get(oid, (0, 0.0))
                    self._borrow_pins[oid] = (count + 1, expiry)
                    # A borrower registered: shorten (never extend) the
                    # transit window — other still-in-flight handoffs
                    # get TRANSIT_GRACE_S to register; after that the
                    # borrow pins carry the object.
                    texp = self._transit_pins.get(oid)
                    if texp is not None:
                        self._transit_pins[oid] = min(
                            texp, now + self.TRANSIT_GRACE_S)
                elif kind == "refresh":
                    pin = self._borrow_pins.get(oid)
                    if pin is not None:
                        self._borrow_pins[oid] = (pin[0], expiry)
                elif kind == "release":
                    count, _ = self._borrow_pins.get(oid, (0, 0.0))
                    if count > 1:
                        self._borrow_pins[oid] = (count - 1, expiry)
                    else:
                        self._borrow_pins.pop(oid, None)
                elif kind == "transit":
                    self._add_transit_pin_locked(oid)
            for oid in touched:
                if (oid in self._deferred_free
                        and not self._has_pins_locked(oid)
                        and oid not in self._refcounts):
                    self._free_owned_locked(oid)

    def _pin_task_deps(self, deps, fut: Future) -> None:
        """Pin a submitted task's argument objects until it completes
        (ref: reference_count.h:61 — 'Add references for the object
        dependencies of a submitted task'). Without this, the caller
        dropping its arg ObjectRefs after .remote() lets the free path
        delete the objects from store+directory while the task is still
        in flight — its arg fetch then stalls on an object that no
        longer exists anywhere (observed intermittently in the sort
        exchange: merge tasks racing the free of partition outputs)."""
        if not deps:
            return
        dep_oids = [ObjectID(d) for d in deps]
        with self._lock:
            for oid in dep_oids:
                self._refcounts[oid] += 1

        def unpin(_f):
            if self._shutdown:
                return
            with self._lock:
                for oid in dep_oids:
                    self._decref_locked(oid)

        fut.add_done_callback(unpin)

    def _flush_frees_locked(self) -> None:
        batch, self._free_batch = self._free_batch, []
        if not batch:
            return

        async def free():
            try:
                client = AsyncRpcClient(self.gcs_address)
                await client.call("ObjectDirectory", "free_objects",
                                  object_ids=batch, timeout=30)
                await client.close()
            except Exception as e:  # noqa: BLE001
                logger.debug("free_objects failed: %s", e)

        self.loop_thread.submit(free())

    # ------------------------------------------------------------------
    # object API
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        self._store_local(oid, value)
        ref = ObjectRef(oid, self.address)
        with self._lock:
            self._owned.add(oid)
        return ref

    def _store_local(self, oid: ObjectID, value: Any,
                     is_error: bool = False) -> int:
        from ray_tpu.core.object_store import ObjectExistsError

        meta, buffers = serialization.serialize(value, is_error=is_error)
        try:
            size = self.store.put_serialized(oid, meta, buffers)
        except ObjectExistsError:
            return 0
        # Location registration rides the loop asynchronously: local gets
        # hit the store directly, remote readers poll the directory until
        # the (retried) registration lands — put() itself stays store-speed.
        self.queue_location(oid, size)
        return size

    def queue_location(self, oid: ObjectID, size: int) -> None:
        """Thread-safe enqueue onto the batched location flusher.

        The entry lands in the shared batch directly; the loop is woken
        at most once per burst (coalesced via _loc_wake_pending), so a
        tight put() loop pays one self-pipe write, not one per object."""
        with self._loc_lock:
            self._loc_batch.append((oid.binary(), size))
            if self._loc_wake_pending:
                return
            self._loc_wake_pending = True
        self.loop_thread.loop.call_soon_threadsafe(self._loc_kick)

    async def _flush_locations(self) -> None:
        try:
            while True:
                with self._loc_lock:
                    if not self._loc_batch:
                        break
                    batch, self._loc_batch = self._loc_batch, []
                entries = [(o, self.node_id, s) for o, s in batch]
                gcs = await self._aget_gcs()
                sent = False
                for attempt in range(5):
                    try:
                        await gcs.call("ObjectDirectory", "add_locations",
                                       entries=entries, timeout=30)
                        sent = True
                        break
                    except Exception as e:  # noqa: BLE001
                        logger.debug("add_locations retry %d: %s",
                                     attempt, e)
                        await asyncio.sleep(min(0.1 * (attempt + 1), 1.0))
                if not sent:
                    # GCS outage outlasted the retry window: NEVER drop —
                    # an unregistered stored object is silent data loss
                    # for remote readers. Re-queue and retry later.
                    logger.warning(
                        "add_locations failed %d entries; retrying in 2s",
                        len(batch))
                    with self._loc_lock:
                        self._loc_batch.extend(batch)
                    self.loop_thread.loop.call_later(2.0, self._loc_kick)
                    return
        finally:
            self._loc_flushing = False

    def _loc_kick(self) -> None:
        with self._loc_lock:
            self._loc_wake_pending = False
        if self._loc_batch and not self._loc_flushing:
            self._loc_flushing = True
            asyncio.ensure_future(self._flush_locations())

    INLINE_CACHE_CAP = 10000

    def _cache_inline_locked(self, oid: ObjectID, payload: bytes) -> None:
        if oid not in self._inline_cache:
            if payload == _NONE_PAYLOAD:
                # Canonical None result: share the ONE payload object and
                # skip the eviction ring — a burst of side-effect actor
                # calls would otherwise churn (and spill) the ring with
                # thousands of identical ~100-byte entries. Freed on
                # decref like any owned inline entry, so growth stays
                # bounded by live refs.
                self._inline_cache[oid] = _NONE_PAYLOAD
                return
            self._inline_cache[oid] = payload
            self._inline_cache_order.append(oid)

    def _evict_inline_locked(self) -> None:
        while len(self._inline_cache_order) > self.INLINE_CACHE_CAP:
            old = self._inline_cache_order.popleft()
            payload = self._inline_cache.pop(old, None)
            # The inline cache is the PRIMARY copy of owned small
            # results (no eager store write — see OwnerService): an
            # owned entry with live refs spills to the node store on
            # eviction instead of vanishing.
            if (payload is not None and old in self._owned
                    and self._refcounts.get(old, 0) > 0
                    and not self.store.contains(old)):
                try:
                    self.store.put_raw(old, payload)
                    self.queue_location(old, len(payload))
                except Exception:  # noqa: BLE001 store full: keep the
                    # entry (slightly over cap) — dropping it here would
                    # lose the only copy of a live object.
                    self._inline_cache[old] = payload
                    self._inline_cache_order.append(old)
                    break

    def _cache_inline(self, oid: ObjectID, payload: bytes) -> None:
        with self._lock:
            self._cache_inline_locked(oid, payload)
            self._evict_inline_locked()

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None,
            _priority: Optional[int] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        return [self._get_one(r, deadline, _priority) for r in refs]

    def _get_one(self, ref: ObjectRef, deadline: Optional[float],
                 priority: Optional[int] = None) -> Any:
        oid = ref.id()
        backoff = 0.002
        definite_misses = 0
        first_miss_at: Optional[float] = None
        while True:
            # 1) inline cache
            payload = self._inline_cache.get(oid)
            if payload is not None:
                if payload == _NONE_PAYLOAD:
                    # Dominant actor-call reply shape (methods returning
                    # None): skip the per-get deserialize.
                    return None
                return serialization.deserialize(payload)
            # 2) local store (zero-copy)
            buf = self.store.get_buffer(oid)
            if buf is not None:
                return serialization.deserialize(buf.view)
            # 3) pending local task result
            fut = self._pending_objects.get(oid)
            if fut is not None:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise rexc.GetTimeoutError(ref.hex())
                try:
                    fut.result(timeout=remaining)
                except (TimeoutError, FutureTimeoutError):
                    raise rexc.GetTimeoutError(ref.hex()) from None
                continue
            # 4) remote fetch via directory
            pulled, num_locations = self._try_pull_remote(oid,
                                                          priority=priority)
            if pulled:
                continue  # now in local store
            # 4b) small objects live in their OWNER's inline cache (no
            # eager store write — see OwnerService): on a directory
            # miss, ask the owner directly.
            owner = ref.owner_address
            owner_definitely_missing = False
            if owner and owner != self.address:
                got, producing, absent = self._try_fetch_from_owner(
                    oid, owner)
                if got:
                    continue  # now in the inline cache
                if producing:
                    # The owner is still running the producing task:
                    # not lost, keep polling.
                    num_locations = max(num_locations, 1)
                owner_definitely_missing = absent
            # 5) object lost (no copies anywhere): lineage reconstruction
            if num_locations == 0 and self._maybe_reconstruct(oid, deadline):
                continue
            if num_locations == 0 and owner_definitely_missing \
                    and not self._lineage.get(oid):
                # Nobody has it, the owner isn't producing it, and we
                # cannot reconstruct: surface the loss instead of
                # polling forever (the borrow protocol makes this an
                # exceptional state — owner death or pin-TTL expiry).
                definite_misses += 1
                now = time.monotonic()
                if first_miss_at is None:
                    first_miss_at = now
                if definite_misses >= 10 and now - first_miss_at > 2.0:
                    raise rexc.ObjectLostError(
                        f"object {ref.hex()[:16]} exists nowhere: no "
                        f"store copy, owner {owner} has no value and "
                        f"is not producing it, and this process holds "
                        f"no lineage to reconstruct it")
            else:
                definite_misses = 0
                first_miss_at = None
            if deadline is not None and time.monotonic() >= deadline:
                raise rexc.GetTimeoutError(ref.hex())
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.05)

    OWNER_CLIENT_CAP = 32

    def _try_fetch_from_owner(self, oid: ObjectID, owner_addr: str
                              ) -> Tuple[bool, bool, bool]:
        """Fetch a small object from its owner's inline cache (ref:
        in-band small-object replies via GetObjectStatus). Returns
        (fetched, owner_still_producing, definitely_absent) —
        `definitely_absent` only when the owner ANSWERED and has
        neither the value nor a producing task; an unreachable owner
        is indeterminate (transient restarts must not read as loss)."""
        client = self._owner_clients.get(owner_addr)
        if client is None:
            client = self._owner_clients[owner_addr] = SyncRpcClient(
                owner_addr, self.loop_thread)
            # Bounded: owners churn (max_calls retirement spawns fresh
            # worker addresses), so cap and close the oldest instead of
            # accreting dead-owner clients forever.
            while len(self._owner_clients) > self.OWNER_CLIENT_CAP:
                old = next(iter(self._owner_clients))
                try:
                    self._owner_clients.pop(old).close()
                except Exception:  # noqa: BLE001
                    pass
        try:
            rep = client.call("Owner", "get_object",
                              object_id=oid.binary(), timeout=10)
        except Exception:  # noqa: BLE001 owner gone/unreachable: the
            return False, False, False   # directory/lineage path decides
        payload = rep.get("payload")
        if payload is None:
            pending = bool(rep.get("pending"))
            return False, pending, not pending
        self._cache_inline(oid, payload)
        return True, False, False

    def _try_pull_remote(self, oid: ObjectID,
                         priority: Optional[int] = None
                         ) -> Tuple[bool, int]:
        """Returns (pulled_into_local_store, usable_location_count).

        A node that explicitly answers "missing" evicted its copy without
        telling the directory — such stale locations are removed so an
        object whose every copy was LRU-evicted counts as lost (and
        becomes reconstructable) rather than polling forever. Unreachable
        nodes still count: they may come back. Transfers go through the
        PullManager (dedup + priority + in-flight budget)."""
        from ray_tpu.core.distributed import pull_manager as pm

        info = self.gcs.call("ObjectDirectory", "get_locations",
                             object_id=oid.binary(), timeout=30)
        stale = 0
        candidates = []
        for node in info["nodes"]:
            if node["node_id"] == self.node_id:
                if self.store.contains(oid):
                    continue  # caller re-checks; raced back in
                # Directory lists this node but the store evicted the copy.
                stale += 1
                self._remove_stale_location(oid, node["node_id"])
                continue
            candidates.append((node["node_id"], node["address"]))
        if not candidates:
            return False, len(info["nodes"]) - stale
        pull_t0 = time.time()
        try:
            total_size, stale_nodes = self._pull_manager.pull_sync(
                oid.binary(), candidates, info.get("size") or 1,
                priority=pm.PRIORITY_GET if priority is None else priority)
        except Exception as e:  # noqa: BLE001 transfer timeout/failure:
            # retriable — the caller's get loop keeps polling, exactly as
            # the per-node try/except of the pre-PullManager path did.
            logger.debug("pull of %s failed: %s", oid.hex()[:12], e)
            return False, len(info["nodes"]) - stale
        if total_size is not None:
            # Opt-in transfer profile event: pulls show up on the
            # timeline's node rows next to the tasks that waited on them.
            self.task_events.record_profile(
                f"pull:{oid.hex()[:12]}", "transfer", pull_t0,
                time.time(), object_id=oid.hex(), nbytes=total_size,
                sources=len(candidates))
        for nid in stale_nodes:
            stale += 1
            self._remove_stale_location(oid, nid)
        if total_size is None:
            return False, len(info["nodes"]) - stale
        # The striped pull sealed the bytes straight into the local
        # store (create-then-fill); register the new copy so other
        # processes (e.g. a worker fetching task args) can find it.
        try:
            self.gcs.call("ObjectDirectory", "add_location",
                          object_id=oid.binary(), node_id=self.node_id,
                          size=total_size, timeout=10)
        except Exception:  # noqa: BLE001
            pass
        return True, len(info["nodes"])

    def _remove_stale_location(self, oid: ObjectID, node_id: str) -> None:
        try:
            self.gcs.call("ObjectDirectory", "remove_location",
                          object_id=oid.binary(), node_id=node_id,
                          timeout=10)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------
    # lineage reconstruction (ref: object_recovery_manager.h:41 — the owner
    # resubmits the creating task when all copies of an object are lost)
    # ------------------------------------------------------------------
    def _drop_lineage_locked(self, oid: ObjectID, force: bool = False
                             ) -> None:
        """Drop `oid`'s lineage entry unless downstream lineage pins it;
        when an entry's last output is dropped, unpin (and maybe cascade-
        drop) its dependencies. Caller holds self._lock."""
        if self._lineage_pins.get(oid, 0) > 0:
            if not force:
                return
            if oid in self._lineage:
                logger.warning(
                    "lineage cap evicted pinned entry for %s — downstream "
                    "objects depending on it are no longer reconstructable",
                    oid.hex()[:8])
                if len(self._lineage_evicted) < 100_000:
                    self._lineage_evicted.add(oid)
        entry = self._lineage.pop(oid, None)
        if entry is None:
            return
        entry["live"] -= 1
        if entry["live"] > 0:
            return
        self._lineage_bytes -= entry["nbytes"]
        for dep in entry["deps"]:
            d = ObjectID(dep)
            n = self._lineage_pins.get(d, 0) - 1
            if n > 0:
                self._lineage_pins[d] = n
            else:
                self._lineage_pins.pop(d, None)
                if d not in self._refcounts:
                    self._drop_lineage_locked(d)

    def _maybe_reconstruct(self, oid: ObjectID,
                           deadline: Optional[float] = None) -> bool:
        """Resubmit the creating task of a lost owned object (on a worker
        thread) and wait for it, honoring the caller's deadline. Returns
        True if a reconstruction completed (caller re-checks the store)."""
        with self._lock:
            entry = self._lineage.get(oid)
            if entry is None:
                if oid in self._lineage_evicted:
                    raise rexc.ObjectReconstructionFailedError(
                        f"object {oid.hex()[:8]} lost; its lineage was "
                        f"evicted by the lineage cap "
                        f"(RAY_TPU_MAX_LINEAGE_BYTES)")
                return False
            fut = entry["fut"]
            if fut is None:
                if entry["attempts"] >= entry["max_attempts"]:
                    raise rexc.ObjectReconstructionFailedError(
                        f"object {oid.hex()[:8]} lost and reconstruction "
                        f"failed after {entry['attempts']} attempts")
                entry["attempts"] += 1
                entry["fut"] = fut = Future()
                logger.info("reconstructing lost object %s (attempt %d)",
                            oid.hex()[:8], entry["attempts"])
                threading.Thread(target=self._run_reconstruction,
                                 args=(oid, entry, fut),
                                 daemon=True).start()
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
            raise rexc.GetTimeoutError(oid.hex())
        try:
            fut.result(timeout=remaining)
        except (TimeoutError, FutureTimeoutError):
            # (both spelled out: they only became aliases in Python 3.11)
            raise rexc.GetTimeoutError(oid.hex()) from None
        return True

    def _run_reconstruction(self, oid: ObjectID, entry: dict,
                            fut: Future) -> None:
        try:
            # Grace recheck: location registration is asynchronous (batched
            # add_locations), so a freshly produced object can look lost
            # for a few ms. Never resubmit a task whose result is merely
            # still in flight to the directory.
            time.sleep(0.25)
            info = self.gcs.call("ObjectDirectory", "get_locations",
                                 object_id=oid.binary(), timeout=30)
            if info["nodes"] or self.store.contains(oid):
                with self._lock:
                    entry["attempts"] = max(0, entry["attempts"] - 1)
                fut.set_result(None)  # not lost; caller re-pulls
                return
            self._reconstruct_entry(entry)
            fut.set_result(None)
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)
        finally:
            with self._lock:
                entry["fut"] = None

    def _reconstruct_entry(self, entry: dict) -> None:
        # Recursively restore missing dependencies first (depth-first, like
        # the reference's recursive recovery of task args).
        for dep in entry["deps"]:
            dep_oid = ObjectID(dep)
            if self.store.contains(dep_oid):
                continue
            payload = self._inline_cache.get(dep_oid)
            if payload is not None:
                # Owner still holds the bytes: re-seed the store/directory.
                try:
                    self.store.put_raw(dep_oid, payload)
                    self.gcs.call("ObjectDirectory", "add_location",
                                  object_id=dep, node_id=self.node_id,
                                  size=len(payload), timeout=30)
                    continue
                except Exception:  # noqa: BLE001
                    pass
            # Stale-aware availability check (prunes directory entries for
            # evicted copies); reconstruct when no usable copy remains.
            pulled, usable = self._try_pull_remote(dep_oid)
            if pulled or usable > 0:
                continue
            if not self._maybe_reconstruct(dep_oid):
                raise rexc.ObjectReconstructionFailedError(
                    f"dependency {dep_oid.hex()[:8]} is lost and has no "
                    f"retained lineage — cannot reconstruct")
        spec = entry["spec"]
        spec["attempt"] = spec.get("attempt", 0) + 1
        reply = self._lease_and_push(spec, entry["demand"], entry["sched"])
        for r in reply["results"]:
            if r.inline is not None:
                self._cache_inline(ObjectID(r.oid), r.inline)

    def _open_pull_sink(self, oid_b: bytes, total_size: int):
        """Create-then-fill sink in the local store (striped_pull's
        open_sink fn): received chunks never touch the Python heap
        beyond their in-flight frame."""
        from ray_tpu.core.distributed.transfer import ChunkSink

        return ChunkSink(
            self.store.create_for_receive(ObjectID(oid_b), total_size),
            total_size)

    async def _flush_task_events(self, **payload) -> None:
        """Transport for the TaskEventBuffer: one add_task_events RPC
        (the buffer owns retry/drop policy)."""
        gcs = await self._aget_gcs()
        await gcs.call("TaskEvents", "add_task_events", timeout=10,
                       _caller=(self.node_id, "task-events"), **payload)

    def _record_task_status(self, spec: dict, state: str,
                            ts: Optional[float] = None,
                            error: Optional[str] = None) -> None:
        """Record one status transition for a task spec into the bounded
        pipeline (no-op when task events are off; never blocks)."""
        opts = spec.get("options") or {}
        self.task_events.record_status(
            spec["task_id"].hex(), spec.get("attempt", 0), state, ts=ts,
            error=error, name=opts.get("name"),
            job_id=spec.get("job_id"), actor_id=spec.get("actor_id"))

    def _stamp_submit(self, spec: dict) -> None:
        """Submission-side history rides the SPEC, not a separate event:
        the executor folds submit/lease timestamps into its single
        terminal record, so the happy path ships ONE wire record per
        attempt instead of a driver record + an executor record merged
        at the GCS (half the flush volume — on a 1-core host the
        telemetry pipeline's CPU IS task throughput). The driver-side
        buffer still reports tasks that FAIL before reaching a worker
        (_record_driver_failure)."""
        spec["submit_ts"] = time.time()
        spec["submit_ctx"] = self._submit_identity

    def _record_driver_failure(self, spec: dict, error) -> None:
        """Terminal event for a task that died driver-side (lease
        refused, retries exhausted, cancelled while queued): no executor
        ever saw it, so no one else will report it. This is the rare
        complement of the executor's single-record happy path."""
        opts = spec.get("options") or {}
        te = self.task_events
        task_id = spec["task_id"].hex()
        attempt = spec.get("attempt", 0)
        sub = spec.get("submit_ts")
        if sub is not None:
            ctx = spec.get("submit_ctx") or (None, None)
            te.record_status(task_id, attempt, "SUBMITTED", ts=sub,
                             name=opts.get("name"),
                             job_id=spec.get("job_id"),
                             actor_id=spec.get("actor_id"),
                             submit_node_id=ctx[0], submit_pid=ctx[1])
        te.record_status(task_id, attempt, "FAILED", error=repr(error),
                         name=opts.get("name"),
                         job_id=spec.get("job_id"))

    def prefetch(self, refs: List[ObjectRef]) -> None:
        """Best-effort background pulls at the lowest priority (ref: the
        reference's prefetch/wait request class, pull_manager.h:52) —
        dataset pipelines warm the local store without competing with
        blocking gets."""
        def run():
            from ray_tpu.core.distributed import pull_manager as pm

            # The producer's directory registration is asynchronous
            # (batched add_locations), so a single attempt right after
            # task completion races it — retry for a bounded window.
            # ROUND-ROBIN over the batch each sweep: a ref whose location
            # never appears must not starve the refs that are available
            # right now (this is the dataset-pipeline warming path).
            # The window must absorb worst-case control-plane stalls on a
            # loaded host (a 30s directory-lookup timeout per sweep is
            # possible): 60s gave up after ~2 slow sweeps and the warm
            # never landed, so the budget is several slow sweeps deep —
            # this is a daemon thread, so patience costs nothing.
            remaining = [r.id() for r in refs]
            deadline = time.monotonic() + 300.0
            backoff = 0.05
            while (remaining and not self._shutdown
                   and time.monotonic() < deadline):
                still = []
                for oid in remaining:
                    try:
                        if (self._inline_cache.get(oid) is not None
                                or self.store.contains(oid)):
                            continue
                        pulled, _ = self._try_pull_remote(
                            oid, priority=pm.PRIORITY_PREFETCH)
                        if pulled:
                            continue
                    except Exception:  # noqa: BLE001 best effort
                        pass
                    still.append(oid)
                remaining = still
                if remaining:
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 1.0)

        threading.Thread(target=run, daemon=True,
                         name="prefetch").start()

    def push_object(self, ref: ObjectRef, target_node_id: str,
                    timeout: float = 150.0) -> bool:
        """Proactively replicate an object to another node's store (ref:
        push_manager.h:30) — pre-stage data where work will run."""
        oid = ref.id()
        nodes = {n["node_id"]: n
                 for n in self.gcs.call("NodeInfo", "list_nodes",
                                        timeout=30)
                 if n["alive"]}
        target = nodes.get(target_node_id)
        if target is None:
            return False
        info = self.gcs.call("ObjectDirectory", "get_locations",
                             object_id=oid.binary(), timeout=30)
        holders = [n["node_id"] for n in info["nodes"]]
        if self.store.contains(oid) and self.node_id not in holders:
            holders.append(self.node_id)  # registration still in flight
        if target_node_id in holders:
            return True
        # Prefer this node's daemon as the pusher, else any ALIVE holder.
        if self.node_id in holders:
            holder_id = self.node_id
        else:
            holder_id = next((h for h in holders if h in nodes), None)
        if holder_id is None or holder_id not in nodes:
            return False
        client = SyncRpcClient(nodes[holder_id]["address"],
                               self.loop_thread)
        try:
            reply = client.call("NodeDaemon", "push_object",
                                object_id=oid.binary(),
                                target_address=target["address"],
                                timeout=timeout)
            return bool(reply.get("ok"))
        finally:
            client.close()

    def broadcast_object(self, ref: ObjectRef, node_ids: List[str],
                         timeout: float = 600.0) -> dict:
        """Pre-stage one object onto MANY nodes through the daemon
        relay tree (node_daemon.broadcast_object): the holder serves
        only its fanout children and the tree pipelines chunk relays,
        so weight-style 1->N distribution costs the owner fanout*size
        of uplink instead of N*size. Returns the daemon's verdict
        ({ok, nodes, bytes, errors})."""
        oid = ref.id()
        nodes = {n["node_id"]: n
                 for n in self.gcs.call("NodeInfo", "list_nodes",
                                        timeout=30)
                 if n["alive"]}
        info = self.gcs.call("ObjectDirectory", "get_locations",
                             object_id=oid.binary(), timeout=30)
        holders = [n["node_id"] for n in info["nodes"]]
        if self.store.contains(oid) and self.node_id not in holders:
            holders.append(self.node_id)  # registration still in flight
        if self.node_id in holders:
            holder_id = self.node_id
        else:
            holder_id = next((h for h in holders if h in nodes), None)
        if holder_id is None or holder_id not in nodes:
            return {"ok": False, "nodes": 0,
                    "errors": ["no live node holds the object"]}
        targets = [nodes[nid]["address"] for nid in node_ids
                   if nid in nodes and nid != holder_id
                   and nid not in holders]
        if not targets:
            return {"ok": True, "nodes": 0, "errors": []}
        client = SyncRpcClient(nodes[holder_id]["address"],
                               self.loop_thread)
        try:
            return client.call("NodeDaemon", "broadcast_object",
                               object_id=oid.binary(), targets=targets,
                               timeout=timeout)
        finally:
            client.close()

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        # Remote refs need a GCS directory lookup; back those off per-ref so
        # a long wait() doesn't poll the control plane every loop tick.
        gcs_next: Dict[bytes, float] = {}
        gcs_interval: Dict[bytes, float] = {}
        while True:
            still = []
            for r in pending:
                if self._is_ready(r, gcs_next, gcs_interval):
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        ready = ready[:num_returns]
        return ready, [r for r in refs if r not in ready]

    def _is_ready(self, ref: ObjectRef,
                  gcs_next: Optional[Dict[bytes, float]] = None,
                  gcs_interval: Optional[Dict[bytes, float]] = None) -> bool:
        oid = ref.id()
        if oid in self._inline_cache or self.store.contains(oid):
            return True
        fut = self._pending_objects.get(oid)
        if fut is not None:
            return fut.done()
        key = oid.binary()
        now = time.monotonic()
        if gcs_next is not None and now < gcs_next.get(key, 0.0):
            return False
        info = self.gcs.call("ObjectDirectory", "get_locations",
                             object_id=oid.binary(), timeout=30)
        if gcs_next is not None and gcs_interval is not None:
            interval = min(gcs_interval.get(key, 0.025) * 2, 1.0)
            gcs_interval[key] = interval
            gcs_next[key] = now + interval
        return bool(info["nodes"])

    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def waiter():
            try:
                fut.set_result(self.get([ref])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=waiter, daemon=True).start()
        return fut

    # ------------------------------------------------------------------
    # internal KV (ref: gcs InternalKV client surface, _private/gcs_utils.py)
    # ------------------------------------------------------------------
    def kv_put(self, namespace: bytes, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        return self.gcs.call("KV", "put", namespace=ns, key=key,
                             value=value, overwrite=overwrite, timeout=30)

    def kv_get(self, namespace: bytes, key: bytes) -> Optional[bytes]:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        return self.gcs.call("KV", "get", namespace=ns, key=key, timeout=30)

    def kv_del(self, namespace: bytes, key: bytes) -> bool:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        return self.gcs.call("KV", "delete", namespace=ns, key=key,
                             timeout=30)

    def kv_keys(self, namespace: bytes, prefix: bytes = b"") -> list:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        return self.gcs.call("KV", "keys", namespace=ns, prefix=prefix,
                             timeout=30)

    # ------------------------------------------------------------------
    # function table
    # ------------------------------------------------------------------
    def _export_function(self, func) -> bytes:
        # function_key is cloudpickle + sha1 — hundreds of µs, and it was
        # being paid on EVERY .remote() of the same function (the hottest
        # line of task submission by far). Key by function identity;
        # WeakKeyDictionary so redefined functions don't pin forever.
        try:
            key = self._fn_key_cache.get(func)
        except TypeError:  # unhashable/unweakrefable callable
            key = None
        if key is not None:
            return key
        key, blob = protocol.function_key(func)
        if key not in self._exported_fns:
            self.gcs.call("KV", "put", namespace="fn", key=key, value=blob,
                          overwrite=False, timeout=30)
            self._exported_fns.add(key)
        try:
            self._fn_key_cache[func] = key
        except TypeError:
            pass  # unhashable/unweakrefable callable: just re-hash later
        return key

    def fetch_function(self, key: bytes) -> Any:
        fn = self._fn_cache.get(key)
        if fn is None:
            blob = self.gcs.call("KV", "get", namespace="fn", key=key,
                                 timeout=30)
            if blob is None:
                raise rexc.RayTpuError(f"function {key.hex()} not found")
            fn = cloudpickle.loads(blob)
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # task submission
    # ------------------------------------------------------------------
    def _promote_ref(self, ref: ObjectRef) -> None:
        """Ensure a ref's value is resolvable by another process: if only in
        the inline cache, write it to the shm store + directory."""
        oid = ref.id()
        if self.store.contains(oid):
            return
        payload = self._inline_cache.get(oid)
        if payload is not None:
            try:
                self.store.put_raw(oid, payload)
                self.gcs.call("ObjectDirectory", "add_location",
                              object_id=oid.binary(), node_id=self.node_id,
                              size=len(payload), timeout=30)
            except Exception:  # noqa: BLE001
                pass

    def _normalized_env(self, options: TaskOptions) -> Optional[dict]:
        """Normalize the task/actor runtime env (falls back to the job's;
        packaging uploads are cached per distinct raw spec)."""
        import json as _json

        raw = options.runtime_env or self.job_runtime_env
        if not raw:
            return None
        key = _json.dumps(raw, sort_keys=True, default=str)
        if key not in self._norm_env_cache:
            from ray_tpu import runtime_env as renv

            self._norm_env_cache[key] = renv.normalize(raw, self.kv_put)
        return self._norm_env_cache[key]

    def _scheduling_fields(self, options: TaskOptions) -> dict:
        strategy = "hybrid"
        affinity = None
        soft = False
        placement = None
        st = options.scheduling_strategy
        if isinstance(st, SpreadSchedulingStrategy):
            strategy = "spread"
        elif isinstance(st, NodeAffinitySchedulingStrategy):
            strategy = "node_affinity"
            affinity = st.node_id
            soft = st.soft
        elif isinstance(st, PlacementGroupSchedulingStrategy):
            pg = st.placement_group
            placement = (pg.id.hex(), st.placement_group_bundle_index)
        return {"strategy": strategy, "affinity": affinity, "soft": soft,
                "placement": placement,
                "runtime_env": self._normalized_env(options)}

    def submit_task(self, func, args, kwargs, options: TaskOptions
                    ) -> List[ObjectRef]:
        fn_key = self._export_function(func)
        args_blob, deps = protocol.pack_args(args, kwargs, self._promote_ref)
        task_id = TaskID.generate()
        num_returns = options.num_returns
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(1, num_returns + 1)]
        demand = options.resource_demand(default_cpus=1.0)
        sched = self._scheduling_fields(options)

        fut: Future = Future()
        with self._lock:
            for oid in return_ids:
                self._pending_objects[oid] = fut
                self._owned.add(oid)
        self._pin_task_deps(deps, fut)

        spec = protocol.make_task_spec(
            task_id=task_id.binary(), fn_key=fn_key, args_blob=args_blob,
            num_returns=num_returns, caller_address=self.address,
            job_id=self.job_id,
            options={"max_retries": options.max_retries,
                     "retry_exceptions": options.retry_exceptions,
                     "max_calls": options.max_calls,
                     "name": options.name
                     or getattr(func, "__qualname__", "task")},
        )
        if get_config().tracing_enabled:
            from ray_tpu.util import tracing

            spec["trace_ctx"] = tracing.inject()
        self._stamp_submit(spec)
        if options.max_retries > 0 and get_config().lineage_pinning_enabled:
            with self._lock:
                entry = {"spec": spec, "demand": demand, "sched": sched,
                         "deps": deps, "attempts": 0, "fut": None,
                         "max_attempts": max(1, options.max_retries),
                         "live": len(return_ids),
                         "nbytes": len(args_blob)}
                for oid in return_ids:
                    self._lineage[oid] = entry
                    self._lineage_order.append(oid)
                for dep in deps:
                    d = ObjectID(dep)
                    self._lineage_pins[d] = self._lineage_pins.get(d, 0) + 1
                self._lineage_bytes += entry["nbytes"]
                cap = get_config().max_lineage_bytes
                while self._lineage_order and (
                        len(self._lineage_order) > 20000
                        or self._lineage_bytes > cap):
                    old = self._lineage_order.pop(0)
                    self._drop_lineage_locked(old, force=True)

        # Same batched cross-thread handoff as the actor path: one loop
        # wakeup per submission BURST (see submit_actor_task).
        self._submit_buffer.append(
            ("t", (spec, demand, sched, return_ids, fut, deps)))
        if not self._submit_scheduled:
            self._submit_scheduled = True
            self.loop_thread.loop.call_soon_threadsafe(self._drain_submits)
        return [ObjectRef(oid, self.address) for oid in return_ids]

    def submit_streaming_task(self, func, args, kwargs,
                              options: TaskOptions):
        """num_returns="streaming": run a generator task whose yields
        become refs consumable BEFORE the task finishes (ref:
        `ObjectRefGenerator`, _raylet.pyx:272). See
        core/streaming.py for the discovery design."""
        from ray_tpu.core.streaming import ObjectRefGenerator, StreamState

        fn_key = self._export_function(func)
        args_blob, deps = protocol.pack_args(args, kwargs,
                                             self._promote_ref)
        task_id = TaskID.generate()
        demand = options.resource_demand(default_cpus=1.0)
        sched = self._scheduling_fields(options)
        spec = protocol.make_task_spec(
            task_id=task_id.binary(), fn_key=fn_key, args_blob=args_blob,
            num_returns=0, caller_address=self.address,
            job_id=self.job_id,
            options={"max_retries": options.max_retries,
                     "retry_exceptions": options.retry_exceptions,
                     "streaming": True,
                     "name": options.name
                     or getattr(func, "__qualname__", "task")},
        )
        if get_config().tracing_enabled:
            from ray_tpu.util import tracing

            spec["trace_ctx"] = tracing.inject()
        self._stamp_submit(spec)
        state = StreamState()
        fut: Future = Future()   # pins args until the stream completes
        self._pin_task_deps(deps, fut)
        self._live_streams[task_id.binary()] = None
        self.loop_thread.loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(
                self._run_stream_to_completion(spec, demand, sched,
                                               state, fut)))
        return ObjectRefGenerator(self, task_id, state)

    async def _run_stream_to_completion(self, spec, demand, sched, state,
                                        fut) -> None:
        """Slow-path-only driver for streaming tasks (no lane batching:
        streams are long-running and item delivery is via the store +
        directory, not the reply). Retries restart the generator from
        scratch — item ObjectIDs are attempt-independent, so re-stored
        items are identical and already-consumed refs stay valid."""
        opts = spec["options"]
        max_retries = max(0, opts.get("max_retries", 3))
        attempt = 0
        try:
            while True:
                if spec["task_id"] in self._cancelled_tasks:
                    self._cancelled_tasks.pop(spec["task_id"], None)
                    state.finish(None, rexc.TaskCancelledError(
                        opts.get("name", "task")))
                    return
                spec["attempt"] = attempt
                try:
                    reply = await self._lease_and_push_async(spec, demand,
                                                             sched)
                except rexc.TaskError as e:
                    if opts.get("retry_exceptions") \
                            and attempt < max_retries:
                        attempt += 1
                        continue
                    state.finish(None, e)
                    return
                except asyncio.CancelledError:
                    state.finish(None, rexc.TaskCancelledError(
                        "owner shut down mid-stream"))
                    raise
                except rexc.TaskCancelledError as e:
                    state.finish(None, e)
                    return
                except BaseException as e:  # noqa: BLE001 system failure
                    if attempt < max_retries:
                        attempt += 1
                        # Same blip-survival backoff as the
                        # non-streaming retry loop.
                        await asyncio.sleep(min(0.1 * attempt, 1.0))
                        continue
                    state.finish(None, e if isinstance(e, rexc.RayTpuError)
                                 else rexc.TaskError(
                                     spec["options"].get("name", "task"),
                                     f"stream failed: {e!r}"))
                    return
                results = reply.get("results") or []
                for r in results:
                    if r.inline is not None:
                        self._cache_inline(ObjectID(r.oid), r.inline)
                state.finish(len(results), None)
                return
        finally:
            self._live_streams.pop(spec["task_id"], None)
            if not fut.done():
                fut.set_result(None)

    def _finish_stream_on_cancel(self, state):
        """Done-callback: a cancel sweep (loop shutdown) must release
        stream consumers instead of leaving them to time out."""
        def cb(f):
            if f.cancelled() and not state.done.is_set():
                state.finish(None, rexc.TaskCancelledError(
                    "owner shut down mid-stream"))
        return cb

    def _task_submit_on_loop(self, spec, demand, sched, return_ids, fut,
                             deps=()):
        """Fast path: enqueue straight onto the lane (one future + one
        callback per task, no asyncio.Task). Failures fall back to the
        retrying coroutine.

        Dependency gating (ref: the raylet's dependency manager,
        dependency_manager.h — a task is not dispatched until its args
        are available): a spec whose args reference THIS owner's still-
        pending task returns is held back until those tasks finish.
        Without this, a lease-reuse batch can put consumer before
        producer in ONE worker's sequential run — the consumer blocks
        fetching args its own batch hasn't produced yet (observed: the
        range-partition sort's merge tasks deadlocking behind their
        partition tasks for the full arg-fetch timeout)."""
        if deps:
            blockers = []
            with self._lock:
                for dep in deps:
                    dfut = self._pending_objects.get(ObjectID(dep))
                    if dfut is not None and dfut not in blockers:
                        blockers.append(dfut)
            if blockers:
                remaining = [len(blockers)]

                def on_dep_done(_f):
                    with self._lock:
                        remaining[0] -= 1
                        if remaining[0]:
                            return
                    self.loop_thread.loop.call_soon_threadsafe(
                        self._task_submit_on_loop, spec, demand, sched,
                        return_ids, fut, ())

                for dfut in blockers:
                    dfut.add_done_callback(on_dep_done)
                return
        if self._maybe_lane_submit(spec, demand, sched, return_ids, fut):
            return
        from ray_tpu.runtime_env import env_hash

        key = (tuple(sorted(demand.items())), sched["strategy"],
               sched["affinity"], sched["soft"],
               tuple(sched["placement"]) if sched["placement"] else None,
               env_hash(sched.get("runtime_env")))
        lane = self._lanes.get(key)
        if lane is None:
            lane = self._lanes[key] = _TaskLane(self, demand, sched)
        rfut = self.loop_thread.loop.create_future()
        lane.queue.append((spec, rfut))
        lane.wakeup.set()
        lane._maybe_scale()
        rfut.add_done_callback(
            self._task_reply_cb(spec, demand, sched, return_ids, fut))

    def _task_reply_cb(self, spec, demand, sched, return_ids, fut):
        """Shared completion callback for both dispatch paths (pinned
        lane and lease-reuse lane): finish on success/app error, spill
        to the retrying slow path on any transport/lease failure."""

        def on_done(rf):
            retry = False
            try:
                reply = rf.result()
            except asyncio.CancelledError:
                # Loop shutdown (cancel sweep): don't resubmit — a retry
                # coroutine spawned mid-sweep outlives the drain and dies
                # as a destroyed-pending task at interpreter exit.
                if not fut.done():
                    fut.cancel()
                return
            except BaseException:  # noqa: BLE001 transport/lease failure
                retry = True
                reply = None
            if reply is not None:
                err = reply.get("error")
                if err is None:
                    self._finish_task(return_ids, fut,
                                      results=reply["results"])
                    return
                if isinstance(err, rexc.TaskCancelledError):
                    self._cancelled_tasks.pop(spec["task_id"], None)
                    self._finish_task(return_ids, fut, error=err)
                    return
                if (isinstance(err, rexc.TaskError)
                        and not spec["options"].get("retry_exceptions")):
                    self._finish_task(return_ids, fut, error=err)
                    return
                retry = True
            if retry:
                # Slow path owns the full retry budget.
                asyncio.ensure_future(self._run_task_to_completion_async(
                    spec, demand, sched, return_ids, fut))

        return on_done

    def _lane_stat(self, outcome: str) -> None:
        self.lane_stats[outcome] += 1
        self._m_lane.inc(tags={"outcome": outcome})

    def _maybe_lane_submit(self, spec, demand, sched, return_ids,
                           fut) -> bool:
        """Pinned-lane fast path. True => the call was admitted to a
        warm lane; False => caller proceeds down the lease-reuse path
        (signature still cold, lane ineligible, or backlog spill)."""
        cfg = get_config()
        opts = spec["options"]
        if (not cfg.task_lane_enabled or opts.get("max_calls")
                or opts.get("streaming") or sched["placement"]):
            return False
        from ray_tpu.runtime_env import env_hash

        key = (spec["fn_key"], tuple(sorted(demand.items())),
               sched["strategy"], sched["affinity"], sched["soft"],
               env_hash(sched.get("runtime_env")))
        lane = self._pinned_lanes.get(key)
        if lane is None:
            n = self._lane_calls.get(key, 0) + 1
            self._lane_calls[key] = n
            if n < cfg.task_lane_min_calls:
                self._lane_stat("misses")
                return False
            while len(self._lane_calls) > 4096:  # bound cold signatures
                del self._lane_calls[next(iter(self._lane_calls))]
            lane = _PinnedLane(self, key, demand, sched, spec["fn_key"],
                               opts.get("name", "task"))
            self._pinned_lanes[key] = lane
            self._ensure_lane_reaper()
        rfut = self.loop_thread.loop.create_future()
        if not lane.try_submit(spec, rfut):
            self._lane_stat("spills")
            return False
        self._lane_stat("hits")
        rfut.add_done_callback(
            self._task_reply_cb(spec, demand, sched, return_ids, fut))
        return True

    def _ensure_lane_reaper(self) -> None:
        if self._lane_reaper is not None and not self._lane_reaper.done():
            return
        self._lane_reaper = asyncio.ensure_future(self._lane_reaper_loop())

    async def _lane_reaper_loop(self) -> None:
        """Release idle pinned lanes: a lane that stops being called
        gives its worker back after task_lane_idle_s, so the daemon's
        idle reaping / cold-start accounting works as without lanes."""
        try:
            while True:
                idle_s = max(0.05, get_config().task_lane_idle_s)
                await asyncio.sleep(min(0.5, idle_s / 2))
                now = time.monotonic()
                for lane in list(self._pinned_lanes.values()):
                    if lane.state == "ready" and lane.inflight == 0 \
                            and now - lane.last_used > idle_s:
                        lane.close("idle")
                if not self._pinned_lanes:
                    return
        except asyncio.CancelledError:
            raise

    async def _close_pinned_lanes(self) -> None:
        """Shutdown: unpin every warm lane while the daemons are still
        alive to take the lease back."""
        if self._lane_reaper is not None:
            self._lane_reaper.cancel()
            self._lane_reaper = None
        lanes = list(self._pinned_lanes.values())
        self._pinned_lanes.clear()
        closers = []
        for lane in lanes:
            if lane.state != "closed":
                lane.state = "closed"
                self._lane_stat("closed")
                closers.append(lane._close_async())
        if closers:
            await asyncio.gather(*closers, return_exceptions=True)

    # ------------------------------------------------------------------
    # exclusive lanes (compiled-DAG FunctionNode stages)
    # ------------------------------------------------------------------
    def open_exclusive_lane(self, fn, *, num_cpus: float = 1.0,
                            resources: Optional[Dict[str, float]] = None,
                            timeout: float = 120.0) -> "_PinnedLane":
        """Sync facade: lease + pin a dedicated worker for one
        compiled-DAG FunctionNode stage and open a lane on it. The lane
        is NOT in the shared registry — the DAG owns its lifecycle (and
        the idle reaper never touches it)."""
        fn_key = self._export_function(fn)
        demand = {"CPU": float(num_cpus)} if num_cpus else {}
        for k, v in (resources or {}).items():
            demand[k] = float(v)
        sched = self._scheduling_fields(TaskOptions())
        name = getattr(fn, "__qualname__", "dag_stage")

        async def open_lane():
            lane = _PinnedLane(self, None, demand, sched, fn_key, name,
                               exclusive=True)
            try:
                await lane._open_task
            finally:
                lane._open_task = None
            return lane

        return self.loop_thread.run(open_lane(), timeout=timeout)

    def lane_apply(self, lane: "_PinnedLane", blob: bytes,
                   name: str = "dag_stage") -> Future:
        """Kick off a long-running lane body (a stage loop); returns a
        concurrent future resolving to the worker's {"error": ...} reply
        when the loop exits — the compiled DAG's loop-ref analogue."""
        return asyncio.run_coroutine_threadsafe(
            lane.apply_async(blob, name), self.loop_thread.loop)

    def close_exclusive_lane(self, lane: "_PinnedLane",
                             timeout: float = 10.0) -> None:
        async def close():
            if lane.state != "closed":
                lane.state = "closed"
                self._lane_stat("closed")
                await lane._close_async()

        try:
            self.loop_thread.run(close(), timeout=timeout)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass

    async def _run_task_to_completion_async(self, spec, demand, sched,
                                            return_ids, fut):
        """Lease a worker, push the task, store results; retries on system
        failure (ref: task retry in task_manager.h:208). Runs as a
        coroutine on the RPC loop — thousands of in-flight tasks cost
        coroutines, not threads."""
        opts = spec["options"]
        max_retries = max(0, opts.get("max_retries", 3))
        attempt = 0
        last_err: Optional[BaseException] = None
        while attempt <= max_retries:
            if spec["task_id"] in self._cancelled_tasks:
                self._cancelled_tasks.pop(spec["task_id"], None)
                self._finish_task(return_ids, fut,
                                  error=rexc.TaskCancelledError(
                                      opts.get("name", "task")))
                return
            spec["attempt"] = attempt
            try:
                reply = await self._lease_and_push_async(spec, demand, sched)
            except rexc.TaskError as e:
                # Application error: retry only with retry_exceptions.
                if opts.get("retry_exceptions") and attempt < max_retries:
                    attempt += 1
                    continue
                self._finish_task(return_ids, fut, error=e)
                return
            except asyncio.CancelledError:
                if not fut.done():
                    fut.cancel()
                raise
            except rexc.TaskCancelledError as e:
                self._cancelled_tasks.pop(spec["task_id"], None)
                self._finish_task(return_ids, fut, error=e)
                return
            except BaseException as e:  # noqa: BLE001 system failure
                last_err = e
                attempt += 1
                await asyncio.sleep(min(0.1 * attempt, 1.0))
                continue
            self._finish_task(return_ids, fut, results=reply["results"])
            return
        err = rexc.WorkerCrashedError(
            f"task failed after {max_retries + 1} attempts: {last_err}")
        self._record_driver_failure(spec, err)
        self._finish_task(return_ids, fut, error=err)

    async def _aclient(self, address: str) -> AsyncRpcClient:
        client = self._aclients.get(address)
        if client is None:
            client = AsyncRpcClient(address)
            self._aclients[address] = client
        return client

    async def _aget_gcs(self) -> AsyncRpcClient:
        if self._agcs is None:
            self._agcs = AsyncRpcClient(self.gcs_address)
        return self._agcs

    async def _warm_gcs(self) -> None:
        """Best-effort eager connect; real calls retry lazily anyway."""
        try:
            await (await self._aget_gcs())._ensure_conn()
        except Exception:  # noqa: BLE001 GCS not up yet: first call retries
            pass

    def _lease_and_push(self, spec, demand, sched) -> dict:
        """Sync facade (reconstruction path runs on plain threads)."""
        return self.loop_thread.run(
            self._lease_and_push_async(spec, demand, sched))

    async def _lease_and_push_async(self, spec, demand, sched) -> dict:
        from ray_tpu.runtime_env import env_hash

        key = (tuple(sorted(demand.items())), sched["strategy"],
               sched["affinity"], sched["soft"],
               tuple(sched["placement"]) if sched["placement"] else None,
               env_hash(sched.get("runtime_env")))
        lane = self._lanes.get(key)
        if lane is None:
            lane = self._lanes[key] = _TaskLane(self, demand, sched)
        reply = await lane.submit(spec)
        if reply.get("error") is not None:
            raise reply["error"]
        return reply

    def _finish_task(self, return_ids, fut, results=None, error=None):
        if error is not None:
            payload = serialization.dumps(error, is_error=True)
            for oid in return_ids:
                self._cache_inline(oid, payload)
        else:
            for r in results:
                oid = ObjectID(r.oid)
                if r.inline is not None:
                    self._cache_inline(oid, r.inline)
        state = getattr(fut, "stream_state", None)
        if state is not None and not state.done.is_set():
            state.finish(len(results) if error is None
                         and results is not None else None, error)
        with self._lock:
            for oid in return_ids:
                self._pending_objects.pop(oid, None)
        if not fut.done():
            fut.set_result(None)

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def create_actor(self, cls, args, kwargs, options: TaskOptions
                     ) -> ActorID:
        key, blob = protocol.function_key(cls)
        if key not in self._exported_fns:
            self.gcs.call("KV", "put", namespace="fn", key=key, value=blob,
                          overwrite=False, timeout=30)
            self._exported_fns.add(key)
        args_blob, _ = protocol.pack_args(args, kwargs, self._promote_ref)
        actor_id = ActorID.generate()
        # Actors hold 0 CPUs while alive unless explicitly requested (the
        # reference's default: creation needs a worker, lifetime is free —
        # ref: ray_option_utils actor defaults), so long-lived actors don't
        # starve the task pool.
        demand = options.resource_demand(default_cpus=0.0)
        sched = self._scheduling_fields(options)
        self.gcs.call(
            "ActorManager", "create_actor",
            record={
                "actor_id": actor_id.hex(),
                "cls_blob_key": key,
                "cls_name": getattr(cls, "__name__", "Actor"),
                "args_blob": args_blob,
                "demand": demand,
                "max_restarts": options.max_restarts,
                "name": options.name,
                "namespace": options.namespace or "default",
                "detached": options.lifetime == "detached",
                "owner_job": self.job_id,
                "max_concurrency": options.max_concurrency,
                "concurrency_groups": dict(options.concurrency_groups
                                           or {}),
                "placement": sched["placement"],
                "runtime_env": sched["runtime_env"],
            }, timeout=60)
        return actor_id

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args,
                          kwargs, options: TaskOptions):
        streaming = options.num_returns == "streaming"
        aid = actor_id.hex()
        args_blob, deps = protocol.pack_args(args, kwargs,
                                             self._promote_ref)
        task_id = TaskID.generate()
        num_returns = 0 if streaming else options.num_returns
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(1, num_returns + 1)]
        fut = _LightFuture()
        addr = self.address
        # ONE lock round-trip registers everything the call owns: pending
        # entries, ownership, the returned refs' counts (the refs are
        # created _preregistered below — no per-ref _ref_added), and arg
        # pins. Return refs are self-owned, so _ref_added's borrow branch
        # can never apply; plain increments are equivalent.
        with self._lock:
            pending = self._pending_objects
            owned = self._owned
            refcounts = self._refcounts
            for oid in return_ids:
                pending[oid] = fut
                owned.add(oid)
                refcounts[oid] += 1
            if deps:
                dep_oids = [ObjectID(d) for d in deps]
                for oid in dep_oids:
                    refcounts[oid] += 1
        if deps:
            def unpin(_f, dep_oids=dep_oids):
                if self._shutdown:
                    return
                with self._lock:
                    for oid in dep_oids:
                        self._decref_locked(oid)

            fut.add_done_callback(unpin)
        # Per-(options, method) wire-options cache: the SAME dict object
        # rides every spec for this method, so a burst batch pickles it
        # once (pickle memoizes by identity). Nothing mutates
        # spec["options"] driver-side; executors see a private unpickled
        # copy.
        wire_opts = getattr(options, "_wire_opts", None)
        if wire_opts is None or wire_opts["name"] != method_name:
            wire_opts = {"max_retries": options.max_task_retries,
                         "streaming": streaming,
                         "name": method_name}
            options._wire_opts = wire_opts
        # seq is assigned on the loop at push time, per (actor,
        # incarnation-address) — each restarted incarnation starts at 0,
        # so no cross-incarnation base handshake is needed. Spec built as
        # a literal (one dict op) with the submit stamp folded in — see
        # _stamp_submit for why the stamp rides the spec.
        spec = {
            "task_id": task_id.binary(),
            "fn_key": b"",
            "args_blob": args_blob,
            "num_returns": num_returns,
            "caller_address": addr,
            "job_id": self.job_id,
            "options": wire_opts,
            "actor_id": aid,
            "method_name": method_name,
            "seq": -1,
            "attempt": 0,
            "submit_ts": time.time(),
            "submit_ctx": self._submit_identity,
        }
        if get_config().tracing_enabled:
            from ray_tpu.util import tracing

            spec["trace_ctx"] = tracing.inject()
        gen = None
        if streaming:
            # Same discovery design as streaming tasks
            # (core/streaming.py); the stream state rides the waiter
            # future so every completion path — batch reply, push
            # failure, pending-drain error, cancel sweep — finishes it.
            from ray_tpu.core.streaming import (
                ObjectRefGenerator,
                StreamState,
            )

            state = StreamState()
            fut.stream_state = state
            fut.add_done_callback(self._finish_stream_on_cancel(state))
            tid_bin = task_id.binary()
            self._live_streams[tid_bin] = None
            fut.add_done_callback(
                lambda _f: self._live_streams.pop(tid_bin, None))
            gen = ObjectRefGenerator(self, task_id, state)
        # Batched cross-thread handoff: one loop wakeup per BURST, not
        # per call. A per-call call_soon_threadsafe costs a syscall plus
        # a GIL fight with the busy loop thread (~700µs/submit under a
        # tight submission loop — the wakeup, not the work, dominates).
        self._submit_buffer.append(
            ("a", (aid, spec, return_ids, fut, options)))
        if not self._submit_scheduled:
            self._submit_scheduled = True
            self.loop_thread.loop.call_soon_threadsafe(self._drain_submits)
        if streaming:
            return gen
        return [ObjectRef(oid, addr, _preregistered=True)
                for oid in return_ids]

    def _drain_submits(self) -> None:
        # Clear the flag BEFORE draining: an append racing the drain then
        # schedules a (possibly empty) follow-up instead of being lost.
        self._submit_scheduled = False
        while True:
            try:
                kind, item = self._submit_buffer.popleft()
            except IndexError:
                return
            if kind == "a":
                self._actor_submit_on_loop(*item)
            else:
                self._task_submit_on_loop(*item)

    def _actor_submit_on_loop(self, aid, spec, return_ids, fut, options):
        """Fast path for resolved actors: enqueue onto the per-address
        push batch directly. Unresolved actors AND transport-failure
        retries go through the per-actor FIFO, so seqs are always
        assigned in submission/failure order by ONE drain coroutine
        (racing per-call resolvers would renumber arbitrarily).

        No per-call asyncio Future/done-callback: the whole submission
        context rides the push queue and the batch sender completes or
        retries entries directly — at 10k+ calls/s the per-call future +
        closure machinery was a measurable slice of the loop thread."""
        if spec["task_id"] in self._cancelled_tasks:
            # Cancelled before a seq was assigned: dropping here cannot
            # desync the actor's contiguous ordering.
            self._cancelled_tasks.pop(spec["task_id"], None)
            self._finish_task(return_ids, fut,
                              error=rexc.TaskCancelledError(
                                  spec["options"].get("name", "task")))
            return
        info = self._actor_cache.get(aid)
        if not (info and info["state"] == "ALIVE"):
            self._park_actor_submit(aid, (spec, return_ids, fut, options))
            return
        addr = info["worker_address"]
        self._assign_actor_seq(aid, addr, spec)
        self._enqueue_actor_push(addr, (aid, spec, return_ids, fut,
                                        options))

    def _handle_push_failure(self, aid, spec, return_ids, fut, options,
                             exc) -> None:
        self._actor_cache.pop(aid, None)
        retries = spec.get("_push_retries", 0) + 1
        spec["_push_retries"] = retries
        if retries > max(1, options.max_task_retries):
            self._finish_task(
                return_ids, fut,
                error=rexc.ActorUnavailableError(
                    f"actor call failed after {retries} pushes"))
            return
        self._park_actor_submit(aid, (spec, return_ids, fut, options))

    def _park_actor_submit(self, aid: str, item: tuple) -> None:
        pend = self._actor_pending.get(aid)
        if pend is None:
            pend = self._actor_pending[aid] = deque()
            asyncio.ensure_future(self._drain_actor_pending(aid))
        pend.append(item)

    def _enqueue_actor_push(self, addr: str, item: tuple) -> None:
        q = self._push_queues.get(addr)
        if q is None:
            q = self._push_queues[addr] = deque()
        q.append(item)
        if not self._push_flushing.get(addr):
            self._push_flushing[addr] = True
            asyncio.ensure_future(self._actor_push_flusher(addr))

    async def _drain_actor_pending(self, aid: str) -> None:
        try:
            await self._resolve_actor_async(
                aid, timeout=get_config().actor_creation_timeout_s)
        except asyncio.CancelledError:
            for _, _, fut, _ in self._actor_pending.pop(aid, ()):
                if not fut.done():
                    fut.cancel()
            raise
        except Exception as e:  # noqa: BLE001
            for spec, return_ids, fut, options in self._actor_pending.pop(
                    aid, ()):
                self._finish_task(return_ids, fut, error=e)
            return
        pend = self._actor_pending.pop(aid, deque())
        # Synchronous drain (no awaits): later fast-path submissions
        # cannot interleave ahead of the parked ones.
        while pend:
            spec, return_ids, fut, options = pend.popleft()
            self._actor_submit_on_loop(aid, spec, return_ids, fut, options)

    def _assign_actor_seq(self, aid: str, addr: str, spec: dict) -> None:
        """Per-(actor, incarnation-address) submission ordering: the first
        push a fresh incarnation sees is seq 0 (loop-thread-only, so
        assignment order == submission order). A retry to the SAME address
        keeps its seq (the runtime runs stale-but-valid seqs immediately);
        a retry to a NEW address is renumbered in the new incarnation."""
        if spec.get("_assigned_addr") == addr:
            return
        key = (aid, addr)
        seq = self._actor_seq[key]
        self._actor_seq[key] = seq + 1
        spec["seq"] = seq
        spec["_assigned_addr"] = addr
        spec["order_key"] = f"{self.address}|{addr}"

    async def _actor_push_flusher(self, addr: str) -> None:
        # Drains everything queued this tick into batch RPCs, each sent as
        # an INDEPENDENT task. A batch must never gate the send of later
        # pushes: the worker holds out-of-order seqs until the missing seq
        # arrives, so awaiting one batch before sending the next would
        # deadlock whenever a lower seq landed in a later batch (resolve
        # completion order is not seq order).
        q = self._push_queues[addr]
        try:
            try:
                client = await self._aclient(addr)
            except asyncio.CancelledError:
                # Loop shutdown, not a transport failure: cancel waiters
                # instead of re-parking (a re-park would spawn new drain
                # tasks during the cancel sweep).
                while q:
                    _, _, _, fut, _ = q.popleft()
                    if not fut.done():
                        fut.cancel()
                raise
            except Exception as e:  # noqa: BLE001
                while q:
                    aid, spec, return_ids, fut, options = q.popleft()
                    self._handle_push_failure(aid, spec, return_ids, fut,
                                              options, e)
                return
            burst = False
            while q:
                if burst and len(q) < 256:
                    # Coalescing window: under a submission burst the
                    # producer thread races this drain loop; without the
                    # pause every "batch" is 1-2 specs and the burst
                    # degenerates into thousands of tiny RPCs. A lone
                    # call never waits (burst only set after a >1 batch),
                    # so sync latency is unaffected.
                    await asyncio.sleep(0.0002)
                batch = []
                while q and len(batch) < 256:
                    batch.append(q.popleft())
                burst = len(batch) > 1
                asyncio.ensure_future(self._send_actor_batch(client, batch))
        finally:
            self._push_flushing[addr] = False

    async def _send_actor_batch(self, client: AsyncRpcClient,
                                batch: list) -> None:
        addr = client.address if hasattr(client, "address") else None
        if addr:
            for item in batch:
                self._task_locations[item[1]["task_id"]] = addr
        delta = self._delta_frame(batch)
        try:
            if delta is not None:
                replies = await client.call(
                    "Worker", "push_actor_tasks_delta",
                    template=delta[0], deltas=delta[1], timeout=None)
            else:
                replies = await client.call(
                    "Worker", "push_actor_tasks",
                    specs=[item[1] for item in batch], timeout=None)
        except asyncio.CancelledError:
            # Loop shutdown: cancel the batch, don't re-park it (same
            # respawn-during-cancel-sweep hazard as _TaskLane).
            for _, _, _, fut, _ in batch:
                if not fut.done():
                    fut.cancel()
            raise
        except Exception as e:  # noqa: BLE001
            for aid, spec, return_ids, fut, options in batch:
                self._handle_push_failure(aid, spec, return_ids, fut,
                                          options, e)
            return
        finally:
            for item in batch:
                self._task_locations.pop(item[1]["task_id"], None)
        self._finish_actor_batch(batch, replies)

    @staticmethod
    def _delta_frame(batch: list) -> Optional[tuple]:
        """Compress a same-destination burst into ONE template spec plus
        per-call (task_id, seq, submit_ts) deltas. A tight actor-call
        burst is N copies of the same spec differing only in those three
        fields; shipping the template once cuts the per-call pickle/
        unpickle and spec-dict churn on both ends of the push RPC.
        Returns None (send full specs) for singletons or heterogeneous
        batches — correctness never depends on the delta path."""
        if len(batch) < 2:
            return None
        t = batch[0][1]
        t_aid = t["actor_id"]
        t_method = t["method_name"]
        t_blob = t["args_blob"]
        t_opts = t["options"]
        t_nret = t["num_returns"]
        t_attempt = t["attempt"]
        if "trace_ctx" in t or "_push_retries" in t:
            return None
        deltas = [(t["task_id"], t["seq"], t["submit_ts"])]
        for _, s, _, _, _ in batch[1:]:
            if s["actor_id"] != t_aid \
                    or s["method_name"] != t_method \
                    or (s["args_blob"] is not t_blob
                        and s["args_blob"] != t_blob) \
                    or s["options"] is not t_opts \
                    or s["num_returns"] != t_nret \
                    or s["attempt"] != t_attempt \
                    or "trace_ctx" in s or "_push_retries" in s:
                return None
            deltas.append((s["task_id"], s["seq"], s["submit_ts"]))
        return t, deltas

    def _finish_actor_batch(self, batch: list, replies: list) -> None:
        """Complete a whole reply batch under ONE lock acquisition
        (inline-result caching + pending-object cleanup), then wake the
        waiters lock-free. The payload must be cached BEFORE the pending
        entry is popped, or a concurrent get() finds the object nowhere
        and spuriously attempts reconstruction."""
        with self._lock:
            pending = self._pending_objects
            for (aid, spec, return_ids, fut, options), reply in zip(
                    batch, replies):
                if type(reply) is int:
                    # Wire-compressed single-None reply (see
                    # worker_main.push_actor_tasks): reconstruct from our
                    # own return ids; every such result shares the ONE
                    # canonical payload object.
                    oid = return_ids[0]
                    if oid not in self._inline_cache:
                        self._inline_cache[oid] = _NONE_PAYLOAD
                    pending.pop(oid, None)
                    continue
                err = reply.get("error")
                if isinstance(err, rexc.TaskCancelledError):
                    self._cancelled_tasks.pop(spec["task_id"], None)
                if err is None:
                    for r in reply["results"]:
                        if r.inline is not None:
                            self._cache_inline_locked(ObjectID(r.oid),
                                                      r.inline)
                else:
                    payload = serialization.dumps(err, is_error=True)
                    for oid in return_ids:
                        self._cache_inline_locked(oid, payload)
                for oid in return_ids:
                    pending.pop(oid, None)
            self._evict_inline_locked()
        for (aid, spec, return_ids, fut, options), reply in zip(batch,
                                                                replies):
            state = getattr(fut, "stream_state", None)
            if state is not None and not state.done.is_set():
                err = reply.get("error")
                state.finish(None if err is not None
                             else len(reply.get("results") or ()), err)
            if not fut.done():
                fut.set_result(None)

    async def _resolve_actor_async(self, actor_id_hex: str,
                                   timeout: float = 60.0) -> dict:
        deadline = time.monotonic() + timeout
        gcs = await self._aget_gcs()
        known = ""
        while True:
            info = self._actor_cache.get(actor_id_hex)
            if info and info["state"] == "ALIVE":
                return info
            # Long-poll: the GCS replies on the next state TRANSITION
            # (or its own ~2s timeout), so a pending actor costs one
            # parked RPC instead of a 50ms polling loop per caller.
            info = await gcs.call("ActorManager", "wait_actor",
                                  actor_id=actor_id_hex,
                                  known_state=known, timeout=30)
            if info is None:
                raise rexc.ActorDiedError(actor_id_hex, "actor not found")
            self._actor_cache[actor_id_hex] = info
            if info["state"] == "ALIVE":
                return info
            if info["state"] == "DEAD":
                raise rexc.ActorDiedError(actor_id_hex,
                                          info.get("death_reason", ""))
            if time.monotonic() > deadline:
                raise rexc.GetTimeoutError(
                    f"actor {actor_id_hex[:8]} not ready in {timeout}s "
                    f"(state={info['state']})")
            known = info["state"]

    def get_actor(self, name: str, namespace: Optional[str]) -> ActorID:
        info = self.gcs.call("ActorManager", "get_actor", name=name,
                             namespace=namespace or "default", timeout=30)
        if info is None:
            raise ValueError(f"Failed to look up actor '{name}'")
        return ActorID.from_hex(info["actor_id"])

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.gcs.call("ActorManager", "kill_actor", actor_id=actor_id.hex(),
                      no_restart=no_restart, timeout=30)
        self._actor_cache.pop(actor_id.hex(), None)

    def actor_state(self, actor_id: ActorID) -> str:
        info = self.gcs.call("ActorManager", "get_actor",
                             actor_id=actor_id.hex(), timeout=30)
        return "DEAD" if info is None else info["state"]

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------
    def create_placement_group(self, pg_id, bundles, strategy,
                               name=None, detached=False,
                               bundle_labels=None) -> None:
        self.gcs.call("PlacementGroups", "create_pg", pg_id=pg_id.hex(),
                      bundles=bundles, strategy=strategy, name=name,
                      owner_job=self.job_id, detached=detached,
                      bundle_labels=bundle_labels, timeout=60)

    def get_placement_group(self, pg_id) -> Optional[dict]:
        return self.gcs.call("PlacementGroups", "get_pg", pg_id=pg_id.hex(),
                             timeout=30)

    def wait_placement_group(self, pg_id, known_state: str = "",
                             park_s: float = 2.0) -> Optional[dict]:
        """Long-poll get_placement_group: returns when the gang's state
        differs from `known_state`, or after `park_s`."""
        return self.gcs.call("PlacementGroups", "wait_pg",
                             pg_id=pg_id.hex(), known_state=known_state,
                             park_s=park_s, timeout=park_s + 30)

    def remove_placement_group(self, pg_id) -> None:
        self.gcs.call("PlacementGroups", "remove_pg", pg_id=pg_id.hex(),
                      timeout=60)

    def list_placement_groups(self) -> List[dict]:
        return self.gcs.call("PlacementGroups", "list_pgs", timeout=30)

    def cancel(self, ref, force: bool = False,
               recursive: bool = True) -> None:
        """Cancel the task producing `ref` — an ObjectRef or an
        ObjectRefGenerator (ref: CoreWorker::CancelTask).

        Semantics: a task still QUEUED (lane queue, in-flight batch,
        or retry loop) is dropped and its getters raise
        TaskCancelledError; a task RUNNING pure-Python code is
        interrupted at its next bytecode boundary (KeyboardInterrupt
        injection — a task blocked inside a C call is interrupted when
        it returns); future RETRIES are suppressed either way.
        Cancelling a finished task is a no-op. ACTOR tasks are
        cancellable too: dropped before seq assignment, replied-as-
        cancelled from the ordered queue (seq contiguity preserved), or
        interrupted while running a sync method; async actor methods
        are only cancellable while queued (injecting into the shared
        event loop would break every other in-flight call). STREAMING
        tasks are cancellable through their `ObjectRefGenerator` or any
        stream item ref: the running generator is interrupted and the
        stream finishes with TaskCancelledError (ref: ray.cancel on
        ObjectRefGenerator)."""
        from ray_tpu.core.streaming import ObjectRefGenerator

        if isinstance(ref, ObjectRefGenerator):
            tid = ref._task_id.binary()
            if tid not in self._live_streams:
                return   # stream already finished: no-op
        else:
            oid = ref.id()
            tid = oid.task_id().binary()
            with self._lock:
                if (oid not in self._pending_objects
                        and tid not in self._live_streams):
                    return   # already finished (or unknown): no-op
        self._tombstone(tid)

        def on_loop():
            # Wake lanes so queued entries are swept promptly...
            for lane in self._lanes.values():
                lane.wakeup.set()
            # ...and interrupt the task if a worker is RUNNING it
            # right now (KeyboardInterrupt at the next bytecode
            # boundary; best-effort).
            addr = self._task_locations.get(tid)
            if addr:
                async def fire():
                    try:
                        client = await self._aclient(addr)
                        await client.call("Worker", "cancel_task",
                                          task_id=tid, timeout=10)
                    except Exception:  # noqa: BLE001 best-effort
                        pass
                asyncio.ensure_future(fire())
        try:
            self.loop_thread.loop.call_soon_threadsafe(on_loop)
        except Exception:  # noqa: BLE001 loop shutting down
            pass

    def _tombstone(self, tid: bytes) -> None:
        # Bounded insertion-ordered, mirroring the worker-side
        # _cancelled_here cap: a tombstone whose task already finished
        # (or whose lane never re-pops it) ages out instead of leaking.
        self._cancelled_tasks[tid] = None
        while len(self._cancelled_tasks) > 4096:
            self._cancelled_tasks.pop(next(iter(self._cancelled_tasks)))

    # ------------------------------------------------------------------
    # cluster introspection
    # ------------------------------------------------------------------
    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = defaultdict(float)
        for n in self.gcs.call("NodeInfo", "list_nodes", timeout=30):
            if n["alive"]:
                for k, v in n["total"].items():
                    out[k] += v
        return dict(out)

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = defaultdict(float)
        for n in self.gcs.call("NodeInfo", "list_nodes", timeout=30):
            if n["alive"]:
                for k, v in n["available"].items():
                    out[k] += v
        return dict(out)

    def nodes(self) -> List[dict]:
        return [
            {"NodeID": n["node_id"], "Alive": n["alive"],
             "Resources": n["total"], "Available": n["available"],
             "Address": n["address"]}
            for n in self.gcs.call("NodeInfo", "list_nodes", timeout=30)
        ]

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        uninstall_refcounter()
        with self._lock:
            self._flush_frees_locked()
        # Ship whatever the event pipeline still holds (statuses, spans)
        # before the loop thread dies — the flusher's own tick may be
        # seconds out on an idle-backed-off process.
        try:
            self.task_events.stop()
            self.loop_thread.run(self.task_events.flush_final(), timeout=2)
        except Exception:  # noqa: BLE001
            pass
        if self._pinned_lanes or self._lane_reaper is not None:
            try:
                self.loop_thread.run(self._close_pinned_lanes(), timeout=8)
            except Exception:  # noqa: BLE001
                pass
        if self.is_driver:
            try:
                self.gcs.call("JobManager", "finish_job", job_id=self.job_id,
                              timeout=10)
            except Exception:  # noqa: BLE001
                pass
            self._stop_spawned_processes()
        try:
            self._chunk_fetcher.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.store.disconnect()
        except Exception:  # noqa: BLE001
            pass
        for client in self._owner_clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass
        self._owner_clients.clear()
        if self._owner_server is not None:
            try:
                self.loop_thread.run(self._owner_server.stop(), timeout=3)
            except Exception:  # noqa: BLE001
                pass
        self.loop_thread.stop()

    def _stop_spawned_processes(self) -> None:
        # Reverse order: daemons (which kill their workers on SIGTERM) go
        # down before the GCS.
        procs = list(reversed(getattr(self, "_spawned_processes", [])))
        for p in procs:
            try:
                p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for p in procs:
            try:
                p.wait(timeout=3)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
        tmp = getattr(self, "_cluster_tmpdir", None)
        if tmp:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
