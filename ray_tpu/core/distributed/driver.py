"""Driver bootstrap: start a local cluster (head) or connect to one.

Analogue of the reference node bootstrap (ref: python/ray/_private/node.py
start_head_processes :1315 — GCS server then raylet then auxiliaries;
driver connect worker.py:2176).
"""
from __future__ import annotations

import atexit
import logging
import os
import re
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.core.distributed.core_worker import DistributedCoreWorker

logger = logging.getLogger(__name__)

_HANDSHAKE_TIMEOUT = 60


def child_env() -> Dict[str, str]:
    """Environment for spawned runtime processes: ensures the package root is
    importable even when ray_tpu runs from a source checkout."""
    import ray_tpu

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    env = dict(os.environ)
    parts = [pkg_root] + [p for p in env.get("PYTHONPATH", "").split(":")
                          if p]
    env["PYTHONPATH"] = ":".join(dict.fromkeys(parts))
    # Never inherit a parent-watch aimed at some OTHER process: a child
    # whose getppid() doesn't match would exit at its first poll.
    env.pop("RAY_TPU_WATCH_PPID", None)
    return env


def _read_handshake(proc: subprocess.Popen, pattern: str,
                    what: str) -> Dict[str, str]:
    """Read `KEY=VALUE ...` handshake line from a child's stdout.

    Non-blocking so the deadline holds even if the child is alive but
    silent (a blocking readline() would wait forever)."""
    deadline = time.monotonic() + _HANDSHAKE_TIMEOUT
    rx = re.compile(pattern)
    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    buf = b""
    while time.monotonic() < deadline:
        try:
            chunk = os.read(fd, 4096)
        except BlockingIOError:
            chunk = None
        if chunk:
            buf += chunk
            m = rx.search(buf.decode(errors="replace"))
            if m:
                os.set_blocking(fd, True)
                return m.groupdict()
        elif proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} during startup")
        else:
            time.sleep(0.01)
    raise RuntimeError(f"{what} did not hand-shake within "
                       f"{_HANDSHAKE_TIMEOUT}s")


# Pre-bound at import: preexec_fn runs between fork and exec in the
# child of a (usually multithreaded) parent, where taking the import or
# allocator lock can deadlock — the body must be one pre-resolved C call.
try:
    import ctypes as _ctypes
    import signal as _signal

    _PRCTL = _ctypes.CDLL("libc.so.6", use_errno=True).prctl
    _PDEATHSIG_ARGS = (1, int(_signal.SIGTERM), 0, 0, 0)  # PR_SET_PDEATHSIG
except Exception:  # noqa: BLE001 non-Linux / no libc
    _PRCTL = None


def pdeathsig_preexec():
    """preexec_fn: deliver SIGTERM to the child when its parent dies.

    A SIGKILL'd driver (OOM, `kill -9` on a test run) cannot run its
    atexit cleanup, and without this every GCS/daemon/worker it spawned
    lives on forever — leaked heartbeating clusters that interfere with
    the next run (the reference gets the same effect from raylet's
    parent-death monitoring). Linux-only; harmless no-op elsewhere."""
    if _PRCTL is not None:
        _PRCTL(*_PDEATHSIG_ARGS)


def _die_with_parent_env(env: Dict[str, str]) -> Dict[str, str]:
    """Mark a child to exit when THIS process dies (see watch_parent in
    this module). PR_SET_PDEATHSIG is unusable here: it fires when the
    forking THREAD exits, and the autoscaler launches nodes from
    short-lived threads — daemons got SIGTERM'd moments after boot."""
    env = dict(env)
    env["RAY_TPU_WATCH_PPID"] = str(os.getpid())
    return env


def start_watch_parent_thread() -> None:
    """Child side of die_with_parent: poll until the spawning parent is
    gone (we got reparented), then exit — a SIGKILL'd driver must not
    leave heartbeating clusters behind (ref: raylet parent-death
    monitoring). No-op unless RAY_TPU_WATCH_PPID is set."""
    import threading

    # lint: allow-knob -- spawn-time lifecycle handshake between parent and child, pre-config
    want = os.environ.get("RAY_TPU_WATCH_PPID")
    if not want:
        return
    want_pid = int(want)

    def watch():
        while True:
            time.sleep(1.0)
            if os.getppid() != want_pid:
                os._exit(0)

    threading.Thread(target=watch, daemon=True,
                     name="parent-watch").start()


def start_gcs_process(host: str = "127.0.0.1", port: int = 0,
                      storage_dir: Optional[str] = None,
                      die_with_parent: bool = True) -> tuple:
    cmd = [sys.executable, "-m", "ray_tpu.core.distributed.gcs_server",
           "--host", host, "--port", str(port)]
    if storage_dir:
        cmd += ["--storage-dir", storage_dir]
    env = child_env()
    if die_with_parent:
        env = _die_with_parent_env(env)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=None,
                            env=env)
    info = _read_handshake(proc, r"GCS_PORT=(?P<port>\d+)", "GCS server")
    return proc, f"{host}:{info['port']}"


def start_node_daemon_process(
    gcs_address: str,
    *,
    host: str = "127.0.0.1",
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[dict] = None,
    store_dir: Optional[str] = None,
    object_store_memory: int = 0,
    node_id: Optional[str] = None,
    extra_env: Optional[dict] = None,
    die_with_parent: bool = True,
) -> tuple:
    import json

    cmd = [sys.executable, "-m", "ray_tpu.core.distributed.node_daemon",
           "--gcs-address", gcs_address, "--host", host,
           "--resources", json.dumps(resources or {})]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    if store_dir:
        cmd += ["--store-dir", store_dir]
    if object_store_memory:
        cmd += ["--object-store-memory", str(object_store_memory)]
    if node_id:
        cmd += ["--node-id", node_id]
    env = child_env()
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    if die_with_parent:
        env = _die_with_parent_env(env)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=None,
                            env=env)
    info = _read_handshake(
        proc,
        r"DAEMON_PORT=(?P<port>\d+) NODE_ID=(?P<node_id>\w+) "
        r"STORE_DIR=(?P<store_dir>\S+)",
        "node daemon")
    return proc, {
        "address": f"{host}:{info['port']}",
        "node_id": info["node_id"],
        "store_dir": info["store_dir"],
    }


def connect_or_start_cluster(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[dict] = None,
    namespace: Optional[str] = None,
    object_store_memory: Optional[int] = None,
    log_to_driver: bool = True,
) -> DistributedCoreWorker:
    spawned: List[subprocess.Popen] = []
    if address is None:
        gcs_proc, gcs_address = start_gcs_process()
        spawned.append(gcs_proc)
        daemon_proc, node_info = start_node_daemon_process(
            gcs_address, num_cpus=num_cpus, num_tpus=num_tpus,
            resources=resources,
            object_store_memory=object_store_memory or 0)
        spawned.append(daemon_proc)
    else:
        gcs_address = address
        # Find this host's daemon via the GCS node table.
        from ray_tpu.core.distributed.rpc import EventLoopThread, SyncRpcClient

        loop = EventLoopThread("bootstrap")
        gcs = SyncRpcClient(gcs_address, loop)
        node_info = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            nodes = [n for n in gcs.call("NodeInfo", "list_nodes",
                                         timeout=10) if n["alive"]]
            if nodes:
                # Prefer a daemon whose store dir exists locally (same host).
                local = [n for n in nodes
                         if os.path.isdir(n["store_dir"])]
                chosen = (local or nodes)[0]
                node_info = {"address": chosen["address"],
                             "node_id": chosen["node_id"],
                             "store_dir": chosen["store_dir"]}
                break
            time.sleep(0.2)
        gcs.close()
        loop.stop()
        if node_info is None:
            raise RuntimeError(f"no alive nodes behind GCS at {address}")

    job_id = uuid.uuid4().hex[:8]
    worker = DistributedCoreWorker(
        gcs_address=gcs_address,
        node_id=node_info["node_id"],
        daemon_address=node_info["address"],
        store_dir=node_info["store_dir"],
        job_id=job_id,
        is_driver=True,
        log_to_driver=log_to_driver,
    )
    worker._spawned_processes = spawned
    # Breadcrumb for the CLI (`ray-tpu status` with no --address), like
    # the reference's /tmp/ray/ray_current_cluster. Per-uid dir with 0700
    # so another local user can't plant an address the CLI would trust.
    try:
        import json

        bc_dir = f"/tmp/ray_tpu_{os.getuid()}"
        os.makedirs(bc_dir, mode=0o700, exist_ok=True)
        with open(os.path.join(bc_dir, "last_cluster.json"), "w") as f:
            json.dump({"gcs_address": gcs_address,
                       "ts": time.time()}, f)
    except OSError:
        pass
    worker.gcs.call("JobManager", "register_job", job_id=job_id,
                    driver_address=worker.address, timeout=30)
    return worker
