"""Pull manager: prioritized, deduplicated, bounded object transfer.

Analogue of the reference `PullManager`
(ref: src/ray/object_manager/pull_manager.h:52 — prioritized pull
request queues with an in-flight bandwidth budget; request classes
get > task-arg > prefetch, matching its TaskArgs/Get/Wait bundles).

Why it exists even in a pull-based design: concurrent `get()`s of the
same remote object must share ONE transfer; a storm of pulls must not
hold unbounded chunk buffers in RAM; and a user blocking in `get()`
must cut ahead of background prefetch. All transfer work runs on the
process's RPC loop; sync callers block on a concurrent future.

Two transfer backends:
* striped (default in the core worker): `fetch_chunk` + `open_sink`
  hand each transfer to transfer.striped_pull — chunks stream from ALL
  replica locations at once under a bytes window, landing directly in
  the local store's mmap (create-then-fill). The pull result carries
  the object's size; the bytes are already sealed locally.
* legacy: a whole-object `fetch(address, oid)` callable tried one
  replica at a time, returning the bytes (kept for tests and simple
  embedders).
"""
from __future__ import annotations

import asyncio
import itertools
import logging
from collections import deque
from typing import Any, Awaitable, Callable, Deque, Dict, List, Optional, \
    Tuple

logger = logging.getLogger(__name__)

PRIORITY_GET = 0        # a caller is blocked in ray.get()
PRIORITY_TASK_ARG = 1   # a leased worker needs args to start
PRIORITY_PREFETCH = 2   # speculative (dataset prefetch etc.)

# (result, stale_node_ids): result None => no location produced the
# object; bytes under the legacy backend; the object's total size (int)
# under the striped backend (the data is already in the local store).
PullResult = Tuple[Optional[Any], List[str]]
FetchFn = Callable[[str, bytes], Awaitable[Optional[bytes]]]


class _ClassQueue:
    """Priority-class queue with a reserved minimum-service share.

    A plain priority queue starves the lowest class under sustained
    higher-priority load — observed as dataset-prefetch pulls deferred
    past their deadline while get/task-arg traffic flows.  Here a pop
    normally serves the best (lowest-numbered) non-empty class in FIFO
    order, but every `fifo_every`-th pop serves the GLOBALLY oldest
    queued request regardless of class.  A request at global-FIFO depth
    d is therefore served after at most ``fifo_every * d`` pops — a
    deterministic bound that needs no clocks or aging timers (ref:
    src/ray/object_manager/pull_manager.h:52 — the reference likewise
    keeps lower-priority bundles activatable under quota rather than
    strictly dominated).
    """

    def __init__(self, fifo_every: int = 4):
        self._fifo_every = max(2, fifo_every)
        self._classes: Dict[int, Deque] = {}
        self._pops = 0
        self._event = asyncio.Event()

    def put(self, priority: int, seq: int, item) -> None:
        self._classes.setdefault(priority, deque()).append((seq, item))
        self._event.set()

    async def get(self):
        # Multi-consumer wakeup: re-check emptiness after clear() so a
        # put() racing between the check and the clear is never lost.
        while True:
            live = [(p, d) for p, d in self._classes.items() if d]
            if live:
                break
            self._event.clear()
            if any(self._classes.values()):
                continue
            await self._event.wait()
        self._pops += 1
        if self._pops % self._fifo_every == 0:
            _, d = min(live, key=lambda pd: pd[1][0][0])  # oldest head seq
        else:
            _, d = min(live, key=lambda pd: pd[0])        # best class
        seq, item = d.popleft()
        return seq, item


class PullManager:
    def __init__(self, loop: asyncio.AbstractEventLoop,
                 fetch: Optional[FetchFn] = None,
                 max_concurrent: int = 4,
                 max_inflight_bytes: int = 256 << 20,
                 min_service_every: int = 4,
                 fetch_chunk=None, open_sink=None, metrics=None):
        if fetch is None and (fetch_chunk is None or open_sink is None):
            raise ValueError(
                "PullManager needs a legacy fetch fn or the striped "
                "fetch_chunk + open_sink pair")
        self._loop = loop
        self._fetch = fetch
        self._fetch_chunk = fetch_chunk
        self._open_sink = open_sink
        self._metrics = metrics
        self._max_concurrent = max_concurrent
        self._max_inflight_bytes = max_inflight_bytes
        self._min_service_every = min_service_every
        self._inflight_bytes = 0
        self._queue: Optional[_ClassQueue] = None
        self._inflight: Dict[bytes, asyncio.Future] = {}
        self._seq = itertools.count()      # FIFO within a priority class
        self._started = False
        self._bytes_freed: Optional[asyncio.Event] = None
        # Strong roots: asyncio keeps only weak refs to tasks, and a
        # puller waiting on OUR queue is an unreferenced cycle the GC
        # collects mid-flight (same bug class as EventLoopThread._bg_tasks).
        self._pullers: List[asyncio.Task] = []

    # -- sync facade ----------------------------------------------------
    def pull_sync(self, oid_b: bytes,
                  nodes: List[Tuple[str, str]],   # (node_id, address)
                  size_hint: int,
                  priority: int = PRIORITY_GET,
                  timeout: Optional[float] = 150.0) -> PullResult:
        fut = asyncio.run_coroutine_threadsafe(
            self.pull(oid_b, nodes, size_hint, priority), self._loop)
        return fut.result(timeout)

    # -- async core -----------------------------------------------------
    async def pull(self, oid_b: bytes, nodes: List[Tuple[str, str]],
                   size_hint: int,
                   priority: int = PRIORITY_GET) -> PullResult:
        self._ensure_started()
        existing = self._inflight.get(oid_b)
        if existing is not None:
            # Share the transfer; stale bookkeeping belongs to its owner.
            data = await asyncio.shield(existing)
            return data, []
        fut: asyncio.Future = self._loop.create_future()
        self._inflight[oid_b] = fut
        done: asyncio.Future = self._loop.create_future()
        self._queue.put(
            priority, next(self._seq),
            (oid_b, list(nodes), max(size_hint, 1), fut, done))
        try:
            return await done
        finally:
            self._inflight.pop(oid_b, None)

    def _ensure_started(self) -> None:
        if self._started:
            return
        self._started = True
        self._queue = _ClassQueue(self._min_service_every)
        self._bytes_freed = asyncio.Event()
        for _ in range(self._max_concurrent):
            self._pullers.append(asyncio.ensure_future(self._puller()))

    async def _puller(self) -> None:
        while True:
            _, (oid_b, nodes, size, fut, done) = await self._queue.get()
            # Bandwidth budget: block this puller until the estimated
            # bytes fit (one oversized object is always admitted alone).
            while (self._inflight_bytes > 0
                   and self._inflight_bytes + size
                   > self._max_inflight_bytes):
                self._bytes_freed.clear()
                await self._bytes_freed.wait()
            self._inflight_bytes += size
            try:
                data, stale = await self._transfer(oid_b, nodes)
            except Exception as e:  # noqa: BLE001
                data, stale = None, []
                logger.debug("pull of %s failed: %s", oid_b.hex()[:12], e)
            finally:
                self._inflight_bytes -= size
                self._bytes_freed.set()
            if not fut.done():
                fut.set_result(data)
            if not done.done():
                done.set_result((data, stale))

    async def _transfer(self, oid_b: bytes,
                        nodes: List[Tuple[str, str]]) -> PullResult:
        if self._fetch_chunk is not None:
            from ray_tpu.core.config import get_config
            from ray_tpu.core.distributed.transfer import striped_pull

            cfg = get_config()
            return await striped_pull(
                oid_b, list(nodes), self._fetch_chunk, self._open_sink,
                chunk_bytes=cfg.object_transfer_chunk_bytes,
                window_bytes=cfg.transfer_window_bytes,
                per_source=cfg.transfer_per_source_inflight,
                metrics=self._metrics)
        stale: List[str] = []
        for node_id, address in nodes:
            try:
                data = await self._fetch(address, oid_b)
            except Exception as e:  # noqa: BLE001
                logger.debug("pull from %s failed: %s", address, e)
                continue           # unreachable: node may come back
            if data is None:
                stale.append(node_id)   # answered "missing": evicted
                continue
            return data, stale
        return None, stale
