"""Worker zygote: forkserver-style warm worker template process.

Analogue of the reference worker pool's prestart machinery
(ref: src/ray/raylet/worker_pool.h:347 PrestartWorkers + the idle pool),
taken one step further in the direction CPython itself went with
`multiprocessing`'s forkserver: instead of paying a full interpreter
boot + `import ray_tpu` + RPC-stack import for EVERY worker/actor, the
node daemon launches ONE zygote per runtime-env key. The zygote
pre-imports `worker_main` up to (but not including) any connection or
event-loop setup, then sits single-threaded on a unix socket; each
lease/actor start becomes one `os.fork()` (~ms) whose child completes
only the per-worker setup — worker_id, log redirection, env deltas,
registration with the daemon.

Fork-safety contract: the zygote never creates threads, event loops, or
sockets-to-the-control-plane before forking (the listener socket is
closed in the child). Preloaded modules must be import-side-effect
clean; `threading.active_count() > 1` after preload logs a loud warning
and the daemon's spawn path falls back to cold `subprocess.Popen` when a
fork request fails for any reason. Platforms where fork is unsafe or
unavailable (non-Linux) and containerized/foreign-python runtime envs
never reach this module — `NodeDaemon._zygote_compatible` gates them to
the cold path.

Wire protocol (newline-delimited JSON over a unix stream socket):

    -> {"op": "fork", "worker_id": .., "out": .., "err": .., "env": {..}}
    <- {"ok": true, "pid": 1234}
    -> {"op": "ping"}
    <- {"ok": true, "pid": .., "forks": N, "threads": 1}
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import select
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_FORK_SIGNALS = (signal.SIGTERM, signal.SIGINT, signal.SIGCHLD)


class ZygoteError(Exception):
    """A zygote request failed; the caller should cold-spawn instead."""


# ----------------------------------------------------------------------
# server side (the zygote process itself)
# ----------------------------------------------------------------------
def _preload(modules: List[str]) -> None:
    import importlib

    for mod in modules:
        if not mod:
            continue
        try:
            importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 preload is best-effort
            logger.warning("zygote preload of %s failed: %s", mod, e)


def _child_main(req: dict, args) -> None:
    """Forked child: per-worker setup only, then the normal worker body.
    Must never return into the zygote's serve loop."""
    try:
        # Inherited zygote fds must not outlive the fork: a child keeping
        # the listener open would hold the socket file hostage after a
        # zygote crash.
        os.closerange(3, 256)
        for sig in _FORK_SIGNALS:
            signal.signal(sig, signal.SIG_DFL)
        # PDEATHSIG is cleared by fork: re-arm so workers fate-share with
        # the zygote (which itself fate-shares with the daemon) — a
        # SIGKILL'd daemon must not leak a forked worker tree.
        from ray_tpu.core.distributed.driver import pdeathsig_preexec

        pdeathsig_preexec()
        # Per-worker log files, same layout the cold path gives Popen —
        # the LogMonitor tails them identically.
        out_fd = os.open(req["out"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        err_fd = os.open(req["err"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(out_fd, 1)
        os.dup2(err_fd, 2)
        os.close(out_fd)
        os.close(err_fd)
        os.environ.update(req.get("env") or {})
        os.environ["RAY_TPU_WORKER_ID"] = req["worker_id"]
        # The parent's PRNG state is shared by every fork sibling.
        import random

        random.seed()
        import types

        from ray_tpu.core.distributed import worker_main

        ns = types.SimpleNamespace(
            gcs_address=args.gcs_address,
            daemon_address=args.daemon_address,
            node_id=args.node_id,
            store_dir=args.store_dir,
            worker_id=req["worker_id"],
        )
        worker_main.boot_worker(ns)
        os._exit(0)
    except SystemExit as e:
        os._exit(int(e.code or 0))
    except BaseException:  # noqa: BLE001
        import traceback

        traceback.print_exc(file=sys.stderr)
        sys.stderr.flush()
        os._exit(1)


def _reap_children() -> None:
    """Collect exited fork children so liveness checks in the daemon
    (which reads /proc, not waitpid — it is not the parent) see them
    disappear instead of lingering as zombies."""
    while True:
        try:
            pid, _status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return


def serve(args) -> None:
    import threading

    # Everything a forked child would otherwise import lazily during its
    # boot — paid once here instead of per worker (a cold child burned
    # ~17ms on `import psutil` + the store dlopen alone, the bulk of its
    # core-worker init). All fork-safe: pure module defs, no threads.
    modules = [
        "ray_tpu", "ray_tpu.core.distributed.worker_main",
        "ray_tpu.api", "ray_tpu.core.object_store",
        "ray_tpu.core.distributed.pull_manager",
        "ray_tpu.core.distributed.driver", "psutil",
    ]
    modules += [m.strip() for m in (args.preload or "").split(",")]
    _preload(modules)
    try:
        # dlopen the native store lib in the template (the mapping is
        # inherited over fork; rts_connect still happens per child).
        from ray_tpu.core.object_store import get_lib

        get_lib()
    except Exception as e:  # noqa: BLE001 children fall back to own dlopen
        logger.warning("zygote store-lib preload failed: %s", e)
    if threading.active_count() > 1:
        logger.warning(
            "zygote has %d threads after preload (%s) — forked children "
            "may inherit locked state; consider trimming "
            "RAY_TPU_ZYGOTE_PRELOAD",
            threading.active_count(),
            [t.name for t in threading.enumerate()])

    try:
        os.unlink(args.socket_path)
    except FileNotFoundError:
        pass
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(args.socket_path)
    listener.listen(16)
    logger.info("zygote %d serving on %s (preloaded %d modules)",
                os.getpid(), args.socket_path, len(modules))

    conns: Dict[socket.socket, bytes] = {}
    forks = 0
    while True:
        ready, _, _ = select.select([listener] + list(conns), [], [], 0.25)
        _reap_children()
        for sock in ready:
            if sock is listener:
                conn, _addr = listener.accept()
                conns[conn] = b""
                continue
            try:
                chunk = sock.recv(65536)
            except OSError:
                chunk = b""
            if not chunk:
                sock.close()
                conns.pop(sock, None)
                continue
            conns[sock] = buf = conns[sock] + chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                conns[sock] = buf
                try:
                    req = json.loads(line)
                except ValueError:
                    _reply(sock, {"ok": False, "error": "bad json"})
                    continue
                op = req.get("op")
                if op == "ping":
                    _reply(sock, {"ok": True, "pid": os.getpid(),
                                  "forks": forks,
                                  "threads": threading.active_count()})
                elif op == "shutdown":
                    _reply(sock, {"ok": True})
                    os._exit(0)
                elif op == "fork":
                    sys.stdout.flush()
                    sys.stderr.flush()
                    pid = os.fork()
                    if pid == 0:
                        listener.close()
                        for c in conns:
                            c.close()
                        _child_main(req, args)
                        os._exit(1)  # unreachable
                    forks += 1
                    # The child's pid CANNOT be reaped before this
                    # single-threaded loop reaches waitpid, so the
                    # starttime read here is authoritative — it is the
                    # daemon's proof of pid incarnation (pid_max is
                    # 32768 on small hosts; a 1k-worker pool cycles the
                    # pid space in minutes, and signalling a reused raw
                    # pid kills an innocent process).
                    _reply(sock, {"ok": True, "pid": pid,
                                  "starttime": _proc_starttime(pid)})
                else:
                    _reply(sock, {"ok": False,
                                  "error": f"unknown op {op!r}"})


def _reply(sock: socket.socket, obj: dict) -> None:
    try:
        sock.sendall(json.dumps(obj).encode() + b"\n")
    except OSError:
        pass


def _proc_starttime(pid: int) -> int:
    """Kernel starttime (jiffies since boot, /proc/<pid>/stat field 22)
    of this pid's CURRENT incarnation; 0 if unreadable. (pid, starttime)
    uniquely names a process for the life of the boot — the identity
    check that makes signalling raw non-child pids safe under reuse."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            return int(f.read().rsplit(b")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return 0


# ----------------------------------------------------------------------
# client side (lives in the node daemon)
# ----------------------------------------------------------------------
class ZygoteHandle:
    """Daemon-side handle: the zygote Popen plus its control socket.

    Requests are serialized (the daemon's event loop is single-threaded
    and fork replies arrive in ~ms); every socket error closes the
    connection and raises ZygoteError so the caller can retire this
    zygote and cold-spawn."""

    def __init__(self, proc: subprocess.Popen, socket_path: str,
                 env_key: str = ""):
        self.proc = proc
        self.socket_path = socket_path
        self.env_key = env_key
        self.started_at = time.monotonic()
        self.forks = 0
        self._sock: Optional[socket.socket] = None
        self._rbuf = b""

    def alive(self) -> bool:
        return self.proc.poll() is None

    # -- plumbing -------------------------------------------------------
    def _connect(self, boot_wait: float) -> None:
        if self._sock is not None:
            return
        deadline = time.monotonic() + boot_wait
        while True:
            s = None
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(2.0)
                s.connect(self.socket_path)
                self._sock = s
                self._rbuf = b""
                return
            except OSError as e:
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                if not self.alive():
                    raise ZygoteError(
                        f"zygote exited with code "
                        f"{self.proc.returncode}") from e
                if time.monotonic() >= deadline:
                    raise ZygoteError(
                        f"zygote socket not ready within "
                        f"{boot_wait:.1f}s") from e
                time.sleep(0.02)

    def request(self, obj: dict, timeout: float = 5.0,
                boot_wait: float = 5.0) -> dict:
        self._connect(boot_wait)
        sock = self._sock
        try:
            sock.settimeout(timeout)
            sock.sendall(json.dumps(obj).encode() + b"\n")
            while b"\n" not in self._rbuf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ZygoteError("zygote closed the control socket")
                self._rbuf += chunk
            line, self._rbuf = self._rbuf.split(b"\n", 1)
            return json.loads(line)
        except ZygoteError:
            self._close_sock()
            raise
        except (OSError, ValueError) as e:
            self._close_sock()
            raise ZygoteError(f"zygote request failed: {e!r}") from e

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._rbuf = b""

    # -- operations -----------------------------------------------------
    def fork_worker(self, worker_id: str, out_path: str, err_path: str,
                    env: Optional[Dict[str, str]] = None,
                    boot_wait: float = 5.0) -> "ForkedProc":
        reply = self.request(
            {"op": "fork", "worker_id": worker_id, "out": out_path,
             "err": err_path, "env": env or {}}, boot_wait=boot_wait)
        if not reply.get("ok"):
            raise ZygoteError(f"fork refused: {reply.get('error')}")
        self.forks += 1
        return ForkedProc(int(reply["pid"]),
                          int(reply.get("starttime") or 0))

    def ping(self, boot_wait: float = 5.0) -> dict:
        return self.request({"op": "ping"}, boot_wait=boot_wait)

    def kill(self) -> None:
        self._close_sock()
        try:
            self.proc.kill()
        except Exception:  # noqa: BLE001
            pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


class ForkedProc:
    """Popen-shaped shim for a zygote-forked worker.

    The daemon is NOT the parent of a forked worker (the zygote is, and
    reaps it), so Popen semantics are emulated: liveness comes from
    /proc/<pid>/stat — a Z/X state or a missing entry means dead. The
    exact exit code is not observable from here; -1 stands in (only
    log/reporting paths read it).

    Every check and signal verifies the pid's INCARNATION against the
    starttime the zygote captured at fork. Popen never needs this (the
    kernel holds a child's pid until the parent reaps it), but this
    shim holds raw non-child pids: with pid_max=32768 a 1k-worker pool
    cycles the pid space in minutes, and an unverified kill() here once
    SIGTERM'd the zygote itself through a recycled pid."""

    def __init__(self, pid: int, starttime: int = 0):
        self.pid = pid
        self.starttime = starttime
        self.returncode: Optional[int] = None
        self._last_stat = 0.0

    def _stat(self) -> Optional[Tuple[bytes, int]]:
        """(state_char, starttime) of whatever owns this pid NOW, or
        None if the pid is free."""
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                # fields after the ")" that closes comm (comm may itself
                # contain spaces/parens): [0]=state, [19]=starttime.
                fields = f.read().rsplit(b")", 1)[1].split()
            return fields[0], int(fields[19])
        except (OSError, IndexError, ValueError):
            return None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        # Fast path: one signal-0 syscall. The daemon polls every worker
        # a few times a second — with a 1k-worker warm pool, opening
        # /proc/<pid>/stat each time is a measurable bite of a small
        # host's CPU, while kill(pid, 0) is ~1µs.
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self.returncode = -1
            return self.returncode
        except OSError:
            pass  # EPERM: someone else's pid (reuse); confirm below
        # kill(0) cannot see a ZOMBIE (dead, but unreaped by the zygote
        # for up to one reap cycle, ~0.25s) or a recycled pid: confirm
        # state + incarnation via /proc at most every 5s — at a
        # 1k-worker pool a 2x/s cadence alone cost ~5% of a small
        # host's core in /proc opens.
        now = time.monotonic()
        if now - self._last_stat < 5.0:
            return None
        self._last_stat = now
        st = self._stat()
        if (st is None or st[0] in (b"Z", b"X", b"x")
                or (self.starttime and st[1] != self.starttime)):
            self.returncode = -1
        return self.returncode

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def _signal(self, sig: int) -> None:
        if self.returncode is not None:
            return
        st = self._stat()
        if st is None or (self.starttime and st[1] != self.starttime):
            # Worker already gone; whoever holds the pid now (if anyone)
            # is an innocent bystander — never signal it.
            self.returncode = -1
            return
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            self.returncode = -1


def start_zygote(*, gcs_address: str, daemon_address: str, node_id: str,
                 store_dir: str, socket_path: str, log_path: str,
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 preload: str = "") -> subprocess.Popen:
    """Spawn a zygote process (non-blocking — connect happens lazily on
    the first fork request)."""
    from ray_tpu.core.distributed.driver import (child_env,
                                                 pdeathsig_preexec)

    cmd = [
        sys.executable, "-m", "ray_tpu.core.distributed.worker_zygote",
        "--gcs-address", gcs_address,
        "--daemon-address", daemon_address,
        "--node-id", node_id,
        "--store-dir", store_dir,
        "--socket-path", socket_path,
    ]
    if preload:
        cmd += ["--preload", preload]
    penv = child_env()
    if env:
        penv.update({k: str(v) for k, v in env.items()})
    log_f = open(log_path, "ab")
    try:
        proc = subprocess.Popen(cmd, env=penv, cwd=cwd, stdout=log_f,
                                stderr=log_f,
                                preexec_fn=pdeathsig_preexec)
    finally:
        log_f.close()
    return proc


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--daemon-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--store-dir", required=True)
    parser.add_argument("--socket-path", required=True)
    parser.add_argument("--preload", default="")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="[zygote] %(asctime)s %(levelname)s %(message)s")
    try:
        serve(args)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
