"""Task/actor specifications and submission options.

Analogue of the reference TaskSpecification (ref: src/ray/common/task/
task_spec.h) and the per-task/actor option set centralized in
python/ray/_private/ray_option_utils.py.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, ObjectID, PlacementGroupID, TaskID


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


class SchedulingStrategy:
    """Base for scheduling strategies (ref: python/ray/util/
    scheduling_strategies.py)."""


@dataclasses.dataclass
class DefaultSchedulingStrategy(SchedulingStrategy):
    pass


@dataclasses.dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: str = ""
    soft: bool = False


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: Any = None  # PlacementGroup handle
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class FunctionDescriptor:
    """Identifies a remote function/class; the pickled blob is exported once
    to the control plane's function table keyed by `function_hash`
    (ref: python/ray/_private/function_manager.py)."""

    module: str
    qualname: str
    function_hash: str

    def repr_name(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclasses.dataclass
class TaskOptions:
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    num_gpus: Optional[float] = None  # accepted for API parity; mapped to TPU
    memory: Optional[int] = None
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    num_returns: Any = 1    # int, or "streaming" (generator tasks)
    # Constrain scheduling to nodes advertising an accelerator type
    # (ref: accelerator_type= -> an "accelerator_type:X" resource
    # micro-demand; node daemons advertise theirs, accelerators.py).
    accelerator_type: Optional[str] = None
    # Retire the worker process after this many task executions (ref:
    # max_calls — bounds leaks from native/user code; 0 = unlimited).
    max_calls: int = 0
    max_retries: int = 3
    retry_exceptions: bool = False
    name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    scheduling_strategy: Optional[SchedulingStrategy] = None
    runtime_env: Optional[Dict[str, Any]] = None
    concurrency_groups: Dict[str, int] = dataclasses.field(default_factory=dict)
    enable_task_events: bool = True
    _metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def resource_demand(self, default_cpus: float) -> Dict[str, float]:
        demand: Dict[str, float] = dict(self.resources)
        cpus = self.num_cpus if self.num_cpus is not None else default_cpus
        if cpus:
            demand["CPU"] = cpus
        tpus = self.num_tpus
        if tpus is None and self.num_gpus is not None:
            tpus = self.num_gpus
        if tpus:
            demand["TPU"] = tpus
        if self.memory:
            demand["memory"] = float(self.memory)
        if self.accelerator_type:
            demand[f"accelerator_type:{self.accelerator_type}"] = 0.001
        return demand


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    task_type: TaskType
    function: FunctionDescriptor
    # Serialized (args, kwargs) with top-level ObjectRefs replaced by markers.
    serialized_args: bytes
    arg_refs: List[ObjectID]  # refs the task depends on (top-level args)
    num_returns: int
    resources: Dict[str, float]
    options: TaskOptions
    caller_address: str = ""
    # Actor fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    sequence_number: int = 0
    # Placement
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    # Retry bookkeeping
    attempt_number: int = 0

    def return_ids(self) -> List[ObjectID]:
        return [
            ObjectID.for_task_return(self.task_id, i)
            for i in range(1, self.num_returns + 1)
        ]
