"""Streaming generator returns: consume task outputs as they are yielded.

ref: the reference's `ObjectRefGenerator` (`python/ray/_raylet.pyx:272`,
`num_returns="streaming"`): a generator task's yields become object refs
the caller can iterate BEFORE the task finishes — the substrate its Data
and Serve streaming paths build on.

TPU-first divergence: the reference streams items through the owner's
report RPC; here drivers are not RPC servers (`caller_address` is an
opaque owner id), so in-flight items are discovered through the object
directory — the worker stores each yielded value and registers its
location immediately, and `ObjectRefGenerator.__next__` polls the
directory until the item (or the task-completion reply, which fixes the
final count) arrives. Consumed refs resolve through the ordinary `get`
path (inline-cached from the completion reply when small, pulled from
the producing node's store otherwise).

Error semantics: a generator body that raises AFTER yielding k items
invalidates the stream at the next `__next__` — the raising exception
surfaces there (the reference packs it into the (k+1)-th ref instead;
same information, one hop earlier).

Lifecycle: stream item objects are NOT entered into the distributed
refcount (the item count is unknown at submission); they live in the
producing node's store under ordinary LRU eviction and in the owner's
bounded inline cache. Consume streams promptly or copy items out —
matching the reference's guidance that generator refs are not meant as
long-lived storage.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu import exceptions as rexc
from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef


class LocalRefGenerator:
    """local_mode counterpart of ObjectRefGenerator: refs arrive on a
    queue from the in-process pool task."""

    def __init__(self, items, timeout: float = 300.0):
        self._items = items
        self._timeout = timeout
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_ref(self._timeout)

    def completed(self) -> bool:
        return self._done

    def next_ref(self, timeout: float):
        import queue as _queue

        if self._done:
            raise StopIteration
        try:
            kind, payload = self._items.get(timeout=timeout)
        except _queue.Empty:
            raise rexc.GetTimeoutError(
                f"stream item not produced within {timeout}s") from None
        if kind == "item":
            return payload
        self._done = True
        if kind == "err":
            raise payload
        raise StopIteration


class StreamState:
    """Shared between the owner's stream coroutine and the generator."""

    def __init__(self):
        self.count: Optional[int] = None    # total yields; None = running
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    def finish(self, count: Optional[int],
               error: Optional[BaseException]) -> None:
        self.count = count
        self.error = error
        self.done.set()


class ObjectRefGenerator:
    """Iterate a streaming task's return refs as they are produced.

    Yields `ObjectRef`s (resolve values with `ray_tpu.get`), matching
    the reference's generator semantics. Thread-compatible with the
    owning worker's sync GCS client."""

    def __init__(self, worker, task_id: TaskID, state: StreamState,
                 timeout: float = 300.0):
        self._worker = worker
        self._task_id = task_id
        self._state = state
        self._timeout = timeout
        self._emitted = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self.next_ref(self._timeout)

    def completed(self) -> bool:
        return self._state.done.is_set()

    def next_ref(self, timeout: float) -> ObjectRef:
        """`__next__` with an explicit per-item timeout — for streams
        whose yields are farther apart than the default 300s (long
        epochs, deeply queued tasks)."""
        return self._next_ref(timeout)

    def _next_ref(self, timeout: float) -> ObjectRef:
        i = self._emitted + 1
        oid = ObjectID.for_task_return(self._task_id, i)
        state = self._state
        deadline = time.monotonic() + timeout
        backoff = 0.02
        # Items yielded BEFORE a mid-stream failure stay consumable
        # (reference semantics: the error rides after the produced
        # refs); their directory registration may still be in flight
        # when the failure reply lands, so availability gets a short
        # grace window before the error surfaces.
        error_grace: Optional[float] = None
        while True:
            if self._available(oid):
                break
            if state.done.is_set():
                if state.error is not None:
                    if error_grace is None:
                        error_grace = time.monotonic() + 0.3
                    if time.monotonic() >= error_grace:
                        raise state.error
                elif state.count is None or i > state.count:
                    raise StopIteration
                else:
                    break  # completed: reply registered/cached item i
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise rexc.GetTimeoutError(
                    f"stream item {i} of task "
                    f"{self._task_id.hex()[:16]} not produced within "
                    f"{timeout}s")
            if state.done.is_set():
                # done.wait() returns immediately on a set event — a
                # plain sleep paces the error-grace availability polls
                # instead of hammering the directory.
                time.sleep(min(backoff, remaining))
            else:
                state.done.wait(min(backoff, remaining))
            backoff = min(backoff * 1.6, 0.25)
        self._emitted = i
        return ObjectRef(oid, self._worker.address)

    def _available(self, oid: ObjectID) -> bool:
        """The item exists once the producing worker registered its
        location (or it landed locally via the reply's inline cache)."""
        if self._worker._inline_cache.get(oid) is not None \
                or self._worker.store.contains(oid):
            return True
        try:
            info = self._worker.gcs.call(
                "ObjectDirectory", "get_locations",
                object_id=oid.binary(), timeout=10)
            return bool(info.get("nodes"))
        except Exception:  # noqa: BLE001 transient GCS hiccup
            return False
