"""`ray-tpu` operator CLI.

ref: python/ray/scripts/scripts.py (click group :59 — ray start/status/
timeline/...) + the state CLI (python/ray/util/state/state_cli.py —
`ray list tasks|actors|nodes`). Subcommands talk straight to the GCS over
the pickle-codec RPC; the address comes from --address, RAY_TPU_ADDRESS,
or the breadcrumb the last local driver wrote.

Usage:
    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli list nodes|actors|tasks|jobs|pgs|workers
    python -m ray_tpu.scripts.cli timeline --out trace.json
    python -m ray_tpu.scripts.cli metrics [--node <id-prefix>]
    python -m ray_tpu.scripts.cli stack [--node ID] [--worker PID] \
        [--task ID]        # signal-safe all-thread dumps (GIL-proof)
    python -m ray_tpu.scripts.cli top [--per-node]   # cpu/rss per task
    python -m ray_tpu.scripts.cli profile -d 5 [--task N|--actor A]
    python -m ray_tpu.scripts.cli logs [--dead [WORKER]]
    python -m ray_tpu.scripts.cli serve status
    python -m ray_tpu.scripts.cli serve trace <request-id> [-o out.json]
    python -m ray_tpu.scripts.cli train status
    python -m ray_tpu.scripts.cli train trace <run> [-o out.json]
    python -m ray_tpu.scripts.cli gcs top   # control-plane load shares
    python -m ray_tpu.scripts.cli events [--kind node] [--node ID]
    python -m ray_tpu.scripts.cli doctor    # ranked health findings
    python -m ray_tpu.scripts.cli start --head [--num-cpus N ...]
    python -m ray_tpu.scripts.cli start --address <gcs> [--num-cpus N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

BREADCRUMB = f"/tmp/ray_tpu_{os.getuid()}/last_cluster.json"


def _resolve_address(args) -> str:
    if args.address:
        return args.address
    from ray_tpu.core.config import get_config

    if get_config().address:
        return get_config().address
    try:
        with open(BREADCRUMB) as f:
            return json.load(f)["gcs_address"]
    except (OSError, KeyError, ValueError):
        pass
    sys.exit("error: no cluster address (use --address, RAY_TPU_ADDRESS, "
             "or run a driver on this host first)")


class _Gcs:
    def __init__(self, address: str):
        from ray_tpu.core.distributed.rpc import (
            EventLoopThread,
            SyncRpcClient,
        )

        self._loop = EventLoopThread("cli")
        self.client = SyncRpcClient(address, self._loop)
        self.address = address

    def call(self, service, method, timeout=15, **kw):
        return self.client.call(service, method, timeout=timeout, **kw)

    def daemon(self, address: str):
        from ray_tpu.core.distributed.rpc import SyncRpcClient

        return SyncRpcClient(address, self._loop)


def _fmt_table(rows: List[List[str]], headers: List[str]) -> str:
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(str(c)))
    line = "  ".join(h.ljust(w) for h, w in zip(headers, widths))
    out = [line, "-" * len(line)]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------

def cmd_status(gcs: _Gcs, args) -> None:
    nodes = gcs.call("NodeInfo", "list_nodes")
    alive = [n for n in nodes if n["alive"]]
    total: dict = {}
    avail: dict = {}
    for n in alive:
        for k, v in n["total"].items():
            total[k] = total.get(k, 0) + v
        for k, v in n["available"].items():
            avail[k] = avail.get(k, 0) + v
    actors = gcs.call("ActorManager", "list_actors")
    jobs = gcs.call("JobManager", "list_jobs")
    pgs = gcs.call("PlacementGroups", "list_pgs")
    print(f"cluster @ {gcs.address}")
    print(f"  nodes: {len(alive)} alive / {len(nodes)} total")
    for k in sorted(total):
        if k == "memory":
            print(f"  memory: {avail.get(k, 0) / 1e9:.1f}/"
                  f"{total[k] / 1e9:.1f} GB free")
        else:
            print(f"  {k}: {avail.get(k, 0):g}/{total[k]:g} free")
    states = {}
    for a in actors:
        states[a["state"]] = states.get(a["state"], 0) + 1
    print(f"  actors: {len(actors)} "
          f"({', '.join(f'{k}={v}' for k, v in sorted(states.items()))})"
          if actors else "  actors: 0")
    if pgs:
        by_state: dict = {}
        for pg in pgs:
            by_state[pg["state"]] = by_state.get(pg["state"], 0) + 1
        detail = ", ".join(f"{k}={v}" for k, v in sorted(by_state.items()))
        print(f"  placement groups: {len(pgs)} ({detail})")
        # A gang mid-repair: some bundles placed, some holes being
        # re-reserved — worth a line while it lasts.
        for pg in pgs:
            placed = pg.get("placed", 0)
            total_b = pg.get("bundle_count", 0)
            if pg["state"] == "PENDING" and 0 < placed < total_b:
                print(f"    {pg['pg_id'][:12]} repairing: "
                      f"{placed}/{total_b} bundles placed")
    else:
        print("  placement groups: 0")
    running = [j for j in jobs if not j.get("finished")]
    print(f"  jobs: {len(running)} running / {len(jobs)} total")
    # Observability rollup: task-event completeness + federation health.
    try:
        obs = gcs.call("Metrics", "cluster_summary")
    except Exception:  # noqa: BLE001 — pre-federation GCS
        return
    te = obs.get("task_events", {})
    dropped = (te.get("worker_dropped_status", 0)
               + te.get("worker_dropped_profile", 0))
    print(f"  task events: {te.get('stored', 0)} stored "
          f"({te.get('evicted', 0)} evicted, {dropped} dropped, "
          f"{te.get('gc_events', 0)} gc'd)")
    m = obs.get("metrics", {})
    staleness = m.get("staleness_s", {})
    worst = max(staleness.values(), default=0.0)
    print(f"  metrics federation: {m.get('nodes_reporting', 0)} nodes "
          f"reporting (worst staleness {worst:.1f}s)")
    # GCS load attribution: who is spending the control plane's time.
    gload = (obs.get("gcs") or {}).get("load") or {}
    shares = gload.get("component_handler_share") or {}
    if shares:
        top3 = ", ".join(f"{c} {s:.0%}" for c, s in list(shares.items())[:3])
        slow = (gload.get("slow_handlers") or {}).get("total", 0)
        slow_note = f", {slow} slow handler(s)" if slow else ""
        print(f"  gcs load: {top3} of handler time{slow_note} "
              f"(`ray-tpu gcs top`)")
    hung = obs.get("hung_tasks") or []
    if hung:
        names = ", ".join(
            f"{h.get('name') or 'task'}@{(h.get('node_id') or '?')[:8]}"
            for h in hung[:5])
        more = f" (+{len(hung) - 5} more)" if len(hung) > 5 else ""
        print(f"  HUNG tasks: {len(hung)} — {names}{more}  "
              f"(`ray-tpu stack --task <id>` for stacks)")
    # Active train runs: world size, step rate, goodput — the one-line
    # version of `ray-tpu train status`.
    for run, s in ((obs.get("train") or {}).get("runs") or {}).items():
        if not s.get("active"):
            continue
        line = (f"  train run '{run}': world={s.get('world', 0)} "
                f"steps={s.get('steps', 0)} "
                f"rate={s.get('step_rate', 0.0):.2f}/s")
        if s.get("goodput") is not None:
            line += f" goodput={s['goodput']:.0%}"
        skew = s.get("skew") or {}
        if skew.get("stale_ranks"):
            line += f" STALE ranks {skew['stale_ranks']}"
        print(line + "  (`ray-tpu train status`)")
    # Elastic training plane: recent gang restarts / shrinks / grows.
    try:
        ev = gcs.call("EventLog", "list_events", source="elastic", limit=5)
    except Exception:  # noqa: BLE001 — pre-elastic GCS
        return
    if ev:
        print(f"  elastic events (latest {len(ev)}):")
        for e in ev:
            print(f"    [{e.get('severity', '?')}] {e.get('message', '')}")


def cmd_gcs(gcs: _Gcs, args) -> None:
    """GCS control-plane self-observability (`ray-tpu gcs top`): the
    per-service x per-caller-component load shares the attribution
    sink accumulates, the event-loop audit, and the slow-handler ring
    — the measure-then-shard evidence for the GCS sharding arc."""
    blob = gcs.call("Metrics", "gcs_load")
    load = blob.get("load", {})
    total = load.get("total", {})
    print(f"GCS @ {gcs.address} (id {blob.get('node_id', '?')[:12]}) — "
          f"window {load.get('window_s', 0):.0f}s")
    print(f"  {total.get('requests', 0)} requests / "
          f"{total.get('bytes', 0) / 1e6:.2f} MB in / "
          f"{total.get('handler_s', 0):.3f}s handler time")
    rows = [[r["service"], r["component"], r["requests"],
             f"{r['requests_share']:.1%}", r["bytes"],
             f"{r['handler_s']:.4f}", f"{r['handler_share']:.1%}"]
            for r in load.get("rows", [])[:args.limit]]
    if rows:
        print(_fmt_table(rows, ["SERVICE", "COMPONENT", "REQS", "REQ%",
                                "BYTES", "HANDLER_S", "TIME%"]))
    shares = load.get("component_handler_share") or {}
    if shares:
        print("  by component: "
              + ", ".join(f"{c} {s:.1%}" for c, s in shares.items()))
    loop = blob.get("loop", {})
    print(f"  loop audit: lag last/max "
          f"{loop.get('lag_last_s', 0) * 1000:.1f}/"
          f"{loop.get('lag_max_s', 0) * 1000:.1f} ms, "
          f"backlog {loop.get('backlog', 0)}, "
          f"{loop.get('samples', 0)} samples")
    slow = load.get("slow_handlers", {})
    if slow.get("total"):
        print(f"  slow handlers: {slow['total']} over "
              f"{slow.get('budget_ms', 0):.0f}ms budget")
        for e in slow.get("recent", [])[-3:]:
            who = e.get("caller")
            who_s = f"{who[1]}@{who[0][:8]}" if who else "unknown"
            print(f"    {e['service']}.{e['method']} "
                  f"{e['wall_ms']:.0f}ms caller={who_s} [{e['args']}]")
    flight = blob.get("flight", {})
    print(f"  flight recorder: {flight.get('events', 0)} entries "
          f"({'durable' if flight.get('durable') else 'memory-only'}, "
          f"seq {flight.get('seq', 0)})")


def cmd_events(gcs: _Gcs, args) -> None:
    """Cluster flight recorder (`ray-tpu events`): durable state-
    transition journal, filterable by kind prefix / node / age."""
    import datetime

    since = time.time() - args.since_s if args.since_s else None
    ev = gcs.call("FlightRecorder", "list_events", kind=args.kind,
                  node_id=args.node, since=since, limit=args.limit)
    if not ev:
        print("no matching flight-recorder entries")
        return
    rows = []
    for e in ev:
        ts = datetime.datetime.fromtimestamp(e["ts"]).strftime("%H:%M:%S")
        rows.append([ts, e["kind"], e.get("severity", "INFO"),
                     (e.get("node_id") or "-")[:12], e["message"]])
    print(_fmt_table(rows, ["TIME", "KIND", "SEV", "NODE", "MESSAGE"]))


def cmd_doctor(gcs: _Gcs, args) -> None:
    """Fused health report (`ray-tpu doctor`): ranked findings over
    federated metrics, hung tasks, task-event loss, GCS load shares,
    loop lag, and recent flight-recorder entries."""
    rep = gcs.call("Metrics", "doctor", timeout=60)
    findings = rep.get("findings", [])
    if not findings:
        print(f"cluster @ {gcs.address} healthy — "
              f"{len(rep.get('checks', []))} checks passed")
        return
    print(f"cluster @ {gcs.address} — {len(findings)} finding(s):")
    for i, f in enumerate(findings, 1):
        print(f"{i:3d}. [{f['severity'].upper()} {f['score']:.0f}] "
              f"{f['kind']}: {f['message']}")
        print(f"      hint: {f['hint']}")


def cmd_list(gcs: _Gcs, args) -> None:
    kind = args.kind
    if kind == "nodes":
        rows = [[n["node_id"][:12], "ALIVE" if n["alive"] else "DEAD",
                 n["address"],
                 " ".join(f"{k}={v:g}" for k, v in sorted(
                     n["total"].items()) if k != "memory")]
                for n in gcs.call("NodeInfo", "list_nodes")]
        print(_fmt_table(rows, ["NODE_ID", "STATE", "ADDRESS", "RESOURCES"]))
    elif kind == "actors":
        rows = [[a["actor_id"][:12], a.get("cls_name", ""), a["state"],
                 a.get("name") or "", (a.get("node_id") or "")[:12]]
                for a in gcs.call("ActorManager", "list_actors")]
        print(_fmt_table(rows, ["ACTOR_ID", "CLASS", "STATE", "NAME",
                                "NODE"]))
    elif kind == "tasks":
        events = gcs.call("TaskEvents", "list_events", limit=args.limit)
        rows = [[e["task_id"][:12], e.get("name", ""), e.get("state", ""),
                 f"{(e.get('end_ts', 0) - e.get('start_ts', 0)) * 1000:.1f}",
                 (e.get("node_id") or "")[:12], e.get("error") or ""]
                for e in events]
        print(_fmt_table(rows, ["TASK_ID", "NAME", "STATE", "MS", "NODE",
                                "ERROR"]))
    elif kind == "jobs":
        rows = [[j["job_id"], "FINISHED" if j.get("finished") else "RUNNING",
                 time.strftime("%H:%M:%S",
                               time.localtime(j.get("start_time", 0)))]
                for j in gcs.call("JobManager", "list_jobs")]
        print(_fmt_table(rows, ["JOB_ID", "STATE", "STARTED"]))
    elif kind == "pgs":
        rows = [[p["pg_id"][:12], p["state"], p["strategy"],
                 str(len(p.get("bundles", [])))]
                for p in gcs.call("PlacementGroups", "list_pgs")]
        print(_fmt_table(rows, ["PG_ID", "STATE", "STRATEGY", "BUNDLES"]))
    elif kind == "events":
        import datetime

        rows = [[datetime.datetime.fromtimestamp(e["ts"]).strftime(
                     "%H:%M:%S"),
                 e["source"], e["severity"], e["message"]]
                for e in gcs.call("EventLog", "list_events",
                                  limit=args.limit)]
        print(_fmt_table(rows, ["TIME", "SOURCE", "SEVERITY", "MESSAGE"]))
    elif kind == "workers":
        rows = []
        for n in gcs.call("NodeInfo", "list_nodes"):
            if not n["alive"]:
                continue
            try:
                for w in gcs.daemon(n["address"]).call(
                        "NodeDaemon", "list_workers", timeout=10):
                    rows.append([n["node_id"][:12], w["worker_id"][:12],
                                 w["pid"],
                                 "actor" if w["actor_id"] else "task",
                                 "busy" if w["busy"] else "idle"])
            except Exception as e:  # noqa: BLE001
                rows.append([n["node_id"][:12], f"<unreachable: {e}>",
                             "", "", ""])
        print(_fmt_table(rows, ["NODE", "WORKER_ID", "PID", "KIND",
                                "STATE"]))


def cmd_timeline(gcs: _Gcs, args) -> None:
    from ray_tpu.util.timeline import chrome_trace

    events = gcs.call("TaskEvents", "list_events", limit=args.limit)
    with open(args.out, "w") as f:
        json.dump(chrome_trace(events), f)
    print(f"wrote {len(events)} events to {args.out} "
          f"(open in chrome://tracing)")


def cmd_grafana_out(args) -> None:
    """Generate importable Grafana dashboards + provisioning config
    (ref: grafana_dashboard_factory.py). Metric metadata comes from a
    live node's Prometheus dump when a cluster is reachable, else from
    the known daemon metric set — so this works air-gapped."""
    from ray_tpu.dashboard.grafana import (
        metrics_from_prometheus_text,
        write_dashboards,
    )

    metrics = None
    try:
        gcs = _Gcs(_resolve_address(args))
        for n in gcs.call("NodeInfo", "list_nodes"):
            if not n["alive"]:
                continue
            text = gcs.daemon(n["address"]).call(
                "NodeDaemon", "get_metrics", timeout=10)
            metrics = metrics_from_prometheus_text(text)
            break
    except Exception:  # noqa: BLE001 — no cluster: static fallback
        pass
    for path in write_dashboards(args.grafana_out, metrics=metrics):
        print(path)


def cmd_metrics(gcs: _Gcs, args) -> None:
    if getattr(args, "federated", False):
        # One exposition for the whole cluster, node-labelled, straight
        # from the GCS's syncer-fed federation cache — no per-daemon
        # scrape fan-out.
        print(gcs.call("Metrics", "federated_text"))
        return
    for n in gcs.call("NodeInfo", "list_nodes"):
        if not n["alive"]:
            continue
        if args.node and not n["node_id"].startswith(args.node):
            continue
        print(f"# node {n['node_id'][:12]} @ {n['address']}")
        try:
            print(gcs.daemon(n["address"]).call("NodeDaemon", "get_metrics",
                                                timeout=10))
        except Exception as e:  # noqa: BLE001
            print(f"# unreachable: {e}")


def cmd_serve(gcs: _Gcs, args) -> None:
    """Serving-plane observability (`ray-tpu serve status|trace`):
    status renders the GCS rollup (per-app autoscaling gauges + the
    TTFT/ITL/phase means and counter totals mined from the federated
    serve metrics); trace dumps ONE request's end-to-end span track
    (proxy -> handle -> replica -> engine, resumed hops on their own
    rows) as a perfetto/chrome trace."""
    if args.serve_cmd == "trace":
        from ray_tpu.util.timeline import request_chrome_trace

        spans = gcs.call("TaskEvents", "list_spans",
                         trace_id=args.request_id, limit=10000,
                         timeout=30)
        if not spans:
            sys.exit(f"no spans for request {args.request_id!r} "
                     f"(RAY_TPU_SERVE_TRACE_ENABLED=0, or the span "
                     f"buffer has not flushed yet?)")
        out = args.out or f"trace-{args.request_id[:12]}.json"
        with open(out, "w") as f:
            json.dump(request_chrome_trace(spans), f)
        print(f"wrote {len(spans)} spans to {out} "
              f"(open in https://ui.perfetto.dev)")
        return
    try:
        summary = gcs.call("Metrics", "cluster_summary").get("serve", {})
    except Exception as e:  # noqa: BLE001 — pre-observability GCS
        sys.exit(f"no serve summary from GCS: {e}")
    apps = summary.get("apps") or {}
    latency = summary.get("latency") or {}
    counters = summary.get("counters") or {}
    names = sorted(set(apps) | set(latency) | set(counters))
    if not names:
        print("no serve apps reporting")
        return
    print(f"serve @ {gcs.address}")
    for app in names:
        print(f"  app {app}:")
        gauges = apps.get(app) or {}
        # Per-replica disagg state rides the gauge payload under the
        # non-numeric `_replicas` key: render it as its own section.
        replicas = gauges.get("_replicas") or {}
        numeric = {k: v for k, v in gauges.items()
                   if isinstance(v, (int, float))}
        if numeric:
            print("    gauges: " + "  ".join(
                f"{k}={v:g}" for k, v in sorted(numeric.items())))
        for rid in sorted(replicas):
            ent = replicas[rid] or {}
            parts = [f"role={ent.get('role', 'unified')}"]
            if "prefixes" in ent:
                parts.append(f"prefixes={len(ent['prefixes'] or ())}")
            rails = ent.get("rails")
            if rails:
                parts.append(
                    f"rails={rails.get('mode', 'off')}"
                    f"({rails.get('active', 0)}/{rails.get('width', 0)} "
                    f"active, {rails.get('spilled_total', 0)} spilled)")
            if ent.get("spec_accept_rate") is not None:
                parts.append(
                    f"spec_accept={100 * ent['spec_accept_rate']:.0f}%")
            print(f"    replica {rid}: " + "  ".join(parts))
        lat = latency.get(app) or {}
        line = []
        if "ttft_mean_s" in lat:
            line.append(f"ttft_mean={lat['ttft_mean_s'] * 1e3:.1f}ms")
        if "itl_mean_s" in lat:
            line.append(f"itl_mean={lat['itl_mean_s'] * 1e3:.1f}ms")
        if line:
            print("    latency: " + "  ".join(line))
        phases = lat.get("phase_mean_s") or {}
        if phases:
            print("    phases: " + "  ".join(
                f"{p}={v * 1e3:.1f}ms" for p, v in sorted(phases.items())))
        cts = counters.get(app) or {}
        if cts:
            print("    counters: " + "  ".join(
                f"{k}={v:g}" for k, v in sorted(cts.items())))


def cmd_train(gcs: _Gcs, args) -> None:
    """Train-plane goodput observability (`ray-tpu train ...`):
    status renders the GCS TrainRunState rollup (per-run goodput
    split, step rate, cross-rank skew + blame rank, restart
    accounting, MFU when hinted); trace dumps ONE run's per-rank
    step/phase span tracks as a perfetto/chrome trace."""
    if args.train_cmd == "trace":
        from ray_tpu.util.timeline import train_chrome_trace

        spans = gcs.call("TaskEvents", "list_spans",
                         trace_id=args.run_id, limit=10000, timeout=30)
        if not spans and "#" not in args.run_id:
            spans = [s for s in gcs.call("TaskEvents", "list_spans",
                                         limit=10000, timeout=30)
                     if (s.get("trace_id") or "").startswith(
                         f"{args.run_id}#")]
        if not spans:
            sys.exit(f"no spans for train run {args.run_id!r} "
                     f"(RAY_TPU_TRAIN_OBS_ENABLED=0, or the span "
                     f"buffer has not flushed yet?)")
        out = args.out or f"train-trace-{args.run_id.replace('#', '_')}.json"
        with open(out, "w") as f:
            json.dump(train_chrome_trace(spans), f)
        print(f"wrote {len(spans)} spans to {out} "
              f"(open in https://ui.perfetto.dev)")
        return
    try:
        runs = gcs.call("Train", "summary", timeout=30).get("runs", {})
    except Exception as e:  # noqa: BLE001 — pre-observability GCS
        sys.exit(f"no train summary from GCS: {e}")
    if not runs:
        print("no train runs reporting")
        return
    print(f"train @ {gcs.address}")
    for run in sorted(runs):
        s = runs[run]
        state = "active" if s.get("active") else \
            f"idle {s.get('last_seen_age_s', 0):.0f}s"
        print(f"  run '{run}' ({s.get('run_id')}, attempt "
              f"{s.get('attempt', 0)}, {state}):")
        line = (f"    world={s.get('world', 0)}  steps={s.get('steps', 0)}"
                f"  rate={s.get('step_rate', 0.0):.2f}/s")
        if s.get("restarts"):
            line += (f"  restarts={s['restarts']} "
                     f"(lost {s.get('lost_restart_s', 0):.1f}s)")
        print(line)
        split = s.get("split") or {}
        if split:
            print(f"    goodput: {s.get('goodput', 0):.1%}  ("
                  + "  ".join(f"{k}={v:.1%}" for k, v in split.items())
                  + ")")
        skew = s.get("skew") or {}
        if skew:
            line = (f"    skew: p50={skew.get('p50_step_s', 0) * 1e3:.1f}ms"
                    f"  p99={skew.get('p99_step_s', 0) * 1e3:.1f}ms"
                    f"  p99/p50={skew.get('ratio', 0):.2f}")
            if skew.get("blame_rank") is not None:
                line += f"  blame=rank {skew['blame_rank']}"
            if skew.get("stale_ranks"):
                line += f"  STALE={skew['stale_ranks']}"
            print(line)
        if s.get("achieved_flops"):
            line = f"    flops: {s['achieved_flops']:.3g}/s achieved"
            if s.get("mfu") is not None:
                line += f"  mfu={s['mfu']:.1%}"
            print(line)


def cmd_job(args) -> None:
    """Job submission commands (ref: `ray job submit/status/logs/stop/list`,
    dashboard/modules/job/cli.py). Uses the direct-to-cluster client."""
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    if args.job_cmd == "submit":
        import shlex

        words = args.entrypoint
        if words and words[0] == "--":
            words = words[1:]
        # shlex.join keeps argument boundaries (a bare " ".join would let
        # the shell re-split/interpret `-c "print(1)"`).
        sid = client.submit_job(entrypoint=shlex.join(words),
                                submission_id=args.submission_id)
        print(f"submitted job {sid}")
        if args.wait:
            info = client.wait_until_finished(sid, timeout=args.timeout)
            print(client.get_job_logs(sid), end="")
            print(f"job {sid}: {info.status} {info.message}")
            if info.status != "SUCCEEDED":
                sys.exit(1)
    elif args.job_cmd == "status":
        info = client.get_job_info(args.submission_id)
        print(f"{info.submission_id}: {info.status} {info.message}")
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_cmd == "stop":
        ok = client.stop_job(args.submission_id)
        print("stopped" if ok else "not running")
    elif args.job_cmd == "list":
        rows = [[j.submission_id, j.status,
                 time.strftime("%H:%M:%S",
                               time.localtime(j.start_time or 0)),
                 j.entrypoint[:60]]
                for j in client.list_jobs()]
        print(_fmt_table(rows, ["SUBMISSION_ID", "STATUS", "STARTED",
                                "ENTRYPOINT"]))


def cmd_stack(gcs: _Gcs, args) -> None:
    """Signal-safe all-thread stack dumps from every (matching) live
    worker (ref: `ray stack`): the GCS Diagnosis service fans SIGUSR1/
    faulthandler captures out over all daemons — this works even when a
    worker is wedged in a GIL-holding native call, the case in-process
    sampling (`ray-tpu profile`) can never see. `--task` matches
    RUNNING attempts by task-id/name substring and dumps only their
    workers; identical stacks are grouped across workers at the end."""
    from ray_tpu.util.profiling import summarize_stacks

    worker_id = None
    pids = None
    if args.worker:
        if args.worker.isdigit():
            pids = [int(args.worker)]
        else:
            worker_id = args.worker
    if args.task:
        rows = gcs.call("TaskEvents", "list_events", limit=10000)
        pids = sorted({
            r["pid"] for r in rows
            if r.get("pid") and r.get("state") == "RUNNING"
            and r.get("kind") not in ("span", "profile")
            and (args.task in (r.get("task_id") or "")
                 or args.task in (r.get("name") or ""))})
        if not pids:
            print(f"no RUNNING task matches {args.task!r} "
                  f"(try `ray-tpu list tasks`)")
            return
    results = gcs.call("Diagnosis", "dump_stacks", node_id=args.node,
                       worker_id=worker_id, pids=pids, timeout=90)
    n_ok = 0
    for nres in results:
        if nres.get("error"):
            print(f"== node {nres['node_id'][:12]}: <{nres['error']}>")
            continue
        for w in nres.get("workers", []):
            head = (f"== worker {w['worker_id'][:12]} pid={w['pid']} "
                    f"node={nres['node_id'][:12]}")
            if w.get("actor_id"):
                head += f" actor={w['actor_id'][:12]}"
            print(head)
            if not w.get("ok"):
                print(f"  <dump failed: {w.get('error')}>")
                continue
            n_ok += 1
            if args.raw:
                print(w.get("raw", ""))
                continue
            for t in w.get("threads", []):
                kind = "current thread" if t.get("current") else "thread"
                print(f"  {kind} {t['thread']} (most recent first):")
                for fr in t["frames"]:
                    print(f"    {fr}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
        print(f"raw dumps -> {args.out}")
    groups = summarize_stacks(results)
    if groups and n_ok > 1:
        print("-- identical stacks across workers --")
        for g in groups[:10]:
            print(f"  {g['workers']}/{g['total']} workers at {g['leaf']}")


def cmd_top(gcs: _Gcs, args) -> None:
    """Per-task-name resource usage view (`ray-tpu top`): attempts,
    running/hung counts, summed + max thread CPU-time, RSS deltas and
    peaks — from the per-attempt attribution the executor ships on
    every task-event record; p50/p99 rollups come from the GCS-side
    task summary."""
    rows = gcs.call("TaskEvents", "list_events", limit=args.limit)
    agg: dict = {}
    for r in rows:
        if r.get("kind") in ("span", "profile"):
            continue
        if args.node and not (r.get("node_id") or "").startswith(
                args.node):
            continue
        key = (r.get("name") or "task",
               (r.get("node_id") or "")[:12] if args.per_node else "*")
        a = agg.setdefault(key, {"n": 0, "running": 0, "hung": 0,
                                 "cpu": 0.0, "cpu_max": 0.0,
                                 "rss": 0, "rss_peak": 0})
        a["n"] += 1
        if r.get("state") == "RUNNING":
            a["running"] += 1
        if r.get("hung"):
            a["hung"] += 1
        c = r.get("cpu_time_s") or 0.0
        a["cpu"] += c
        a["cpu_max"] = max(a["cpu_max"], c)
        a["rss"] += r.get("rss_delta_bytes") or 0
        a["rss_peak"] = max(a["rss_peak"], r.get("rss_peak_bytes") or 0)
    if not agg:
        print("no task attempts with attribution in the stored window")
        return
    table = []
    for (name, node), a in sorted(agg.items(),
                                  key=lambda kv: -kv[1]["cpu"]):
        table.append([
            name, node, a["n"], a["running"], a["hung"],
            f"{a['cpu']:.3f}", f"{a['cpu_max']:.3f}",
            f"{a['rss'] / 1e6:.1f}", f"{a['rss_peak'] / 1e6:.1f}"])
    print(_fmt_table(table, ["NAME", "NODE", "ATTEMPTS", "RUN", "HUNG",
                             "CPU_S", "CPU_MAX_S", "RSS_D_MB",
                             "RSS_PEAK_MB"]))
    try:
        summ = gcs.call("TaskEvents", "summarize")
    except Exception:  # noqa: BLE001 — pre-diagnosis GCS
        return
    usage = summ.get("usage") or {}
    if usage:
        print("-- per-name rollups (GCS window) --")
        rows2 = [[name, u["n"], f"{u['cpu_time_s']['p50']:.4f}",
                  f"{u['cpu_time_s']['p99']:.4f}",
                  f"{u['rss_delta_bytes']['p50'] / 1e6:.1f}",
                  f"{u['rss_delta_bytes']['p99'] / 1e6:.1f}"]
                 for name, u in sorted(usage.items())]
        print(_fmt_table(rows2, ["NAME", "N", "CPU_P50_S", "CPU_P99_S",
                                 "RSS_P50_MB", "RSS_P99_MB"]))


def cmd_profile(gcs: _Gcs, args) -> None:
    """Cluster flamegraph (`ray-tpu profile`): fan the sampling
    `profile` RPC out to the matching workers CONCURRENTLY (the capture
    windows overlap, so one wall-clock duration samples the whole
    target set), merge the collapsed stacks into one flamegraph file,
    and annotate the perfetto timeline with the capture window."""
    import asyncio

    from ray_tpu.core.distributed.rpc import AsyncRpcClient
    from ray_tpu.util.profiling import (
        merge_reports, render_report, write_flamegraph_collapsed)

    targets = []
    running_pids = None
    if args.task:
        rows = gcs.call("TaskEvents", "list_events", limit=10000)
        running_pids = {
            (r.get("node_id"), r.get("pid")) for r in rows
            if r.get("pid") and r.get("state") == "RUNNING"
            and r.get("kind") not in ("span", "profile")
            and (args.task in (r.get("task_id") or "")
                 or args.task in (r.get("name") or ""))}
    actor_addrs = None
    if args.actor:
        actor_addrs = {
            a.get("worker_address")
            for a in gcs.call("ActorManager", "list_actors")
            if a and a["actor_id"].startswith(args.actor)
            and a.get("worker_address")}
    for n in gcs.call("NodeInfo", "list_nodes"):
        if not n["alive"]:
            continue
        if args.node and not n["node_id"].startswith(args.node):
            continue
        try:
            workers = gcs.daemon(n["address"]).call(
                "NodeDaemon", "list_workers", timeout=10)
        except Exception:  # noqa: BLE001
            continue
        for w in workers:
            if not w.get("address") or not w.get("alive", True):
                continue
            if args.worker and not (
                    w["worker_id"].startswith(args.worker)
                    or str(w["pid"]) == args.worker):
                continue
            if (running_pids is not None
                    and (n["node_id"], w["pid"]) not in running_pids):
                continue
            if (actor_addrs is not None
                    and w["address"] not in actor_addrs):
                continue
            targets.append({"node_id": n["node_id"], **w})
    if not targets:
        print("no matching live workers to profile")
        return
    print(f"sampling {len(targets)} workers for {args.duration:.1f}s...")

    async def sample():
        clients = [AsyncRpcClient(t["address"]) for t in targets]
        try:
            return await asyncio.gather(
                *(c.call("Worker", "profile", duration_s=args.duration,
                         interval_s=args.interval,
                         timeout=args.duration + 30) for c in clients),
                return_exceptions=True)
        finally:
            for c in clients:
                await c.close()

    t_start = time.time()
    reps = gcs._loop.run(sample(), timeout=args.duration + 60)
    t_end = time.time()
    ok = [(t, r) for t, r in zip(targets, reps) if isinstance(r, dict)]
    for t, r in zip(targets, reps):
        if not isinstance(r, dict):
            print(f"  worker {t['worker_id'][:12]}: <{r!r}>")
    merged = merge_reports([r for _, r in ok])
    print(render_report(merged))
    write_flamegraph_collapsed(merged, args.out)
    print(f"cluster flamegraph (collapsed stacks) -> {args.out}")
    try:
        # Counter-track annotations: the capture windows land on the
        # perfetto timeline next to the tasks they sampled.
        gcs.call("TaskEvents", "add_task_events", profile=[
            {"kind": "profile", "category": "cpu_profile",
             "name": f"cpu_profile:{t['worker_id'][:8]}",
             "start_ts": t_start, "end_ts": t_end,
             "node_id": t["node_id"], "pid": t["pid"],
             "samples": r.get("samples", 0)} for t, r in ok])
    except Exception:  # noqa: BLE001 annotation is best-effort
        pass


def cmd_logs(gcs: _Gcs, args) -> None:
    """Worker log access (ref: `ray logs` CLI, log_monitor tailing):
    dumps the GCS ring buffers (works for DEAD workers too), or streams
    the live pubsub channel with --follow."""
    if args.follow:
        import asyncio

        from ray_tpu.core.distributed.log_monitor import format_log_prefix
        from ray_tpu.core.distributed.rpc import AsyncRpcClient

        async def follow():
            client = AsyncRpcClient(gcs.address)
            try:
                async for rec in client.stream(
                        "Pubsub", "stream_subscribe", channel="logs"):
                    if args.node and not rec["node_id"].startswith(
                            args.node):
                        continue
                    if args.worker and not rec["worker_id"].startswith(
                            args.worker):
                        continue
                    if args.actor and not (rec.get("actor_id")
                                           or "").startswith(args.actor):
                        continue
                    if args.job and rec.get("job_id") != args.job:
                        continue
                    prefix = format_log_prefix(rec)
                    for line in rec["lines"]:
                        print(f"{prefix} {line}", flush=True)
            finally:
                await client.close()

        try:
            asyncio.run(follow())
        except KeyboardInterrupt:
            pass
        return
    worker = args.worker
    if args.dead is not None and args.dead:
        worker = args.dead
    records = gcs.call("LogManager", "tail_logs", node_id=args.node,
                       worker_id=worker, actor_id=args.actor,
                       job_id=args.job, num_lines=args.lines)
    if args.dead is not None:
        # Post-mortem view: only workers NO LONGER alive anywhere (the
        # GCS ring buffers retain their last lines precisely for this).
        alive = set()
        for n in gcs.call("NodeInfo", "list_nodes"):
            if not n["alive"]:
                continue
            try:
                for w in gcs.daemon(n["address"]).call(
                        "NodeDaemon", "list_workers", timeout=10):
                    if w.get("alive", True):
                        alive.add(w["worker_id"])
            except Exception:  # noqa: BLE001 node mid-restart
                continue
        records = [r for r in records if r["worker_id"] not in alive]
        if not records:
            print("no retained logs for dead workers match")
            return
    for rec in sorted(records, key=lambda r: (r["node_id"],
                                              r["worker_id"])):
        who = (f"actor={rec['actor_id'][:12]}" if rec.get("actor_id")
               else f"worker={rec['worker_id'][:12]}")
        print(f"== {who} node={rec['node_id'][:12]} [{rec['stream']}]")
        for line in rec["lines"]:
            print(f"  {line}")


def cmd_dashboard(args) -> None:
    """Serve the web dashboard for a running cluster (ref: `ray
    dashboard`, dashboard/head.py)."""
    import asyncio

    from ray_tpu.dashboard.head import DashboardHead

    address = _resolve_address(args)

    async def run():
        head = DashboardHead(address, args.host, args.port)
        port = await head.start()
        print(f"dashboard at http://{args.host}:{port} (Ctrl-C to stop)")
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


def cmd_start(args) -> None:
    """Start a head (GCS + daemon) or join a worker daemon to a cluster
    (ref: `ray start --head` / `ray start --address=...`)."""
    from ray_tpu.core.distributed.driver import (
        start_gcs_process,
        start_node_daemon_process,
    )

    if args.head:
        gcs_proc, gcs_address = start_gcs_process(die_with_parent=False)
        print(f"GCS started at {gcs_address}")
        os.makedirs(os.path.dirname(BREADCRUMB), mode=0o700, exist_ok=True)
        with open(BREADCRUMB, "w") as f:
            json.dump({"gcs_address": gcs_address, "ts": time.time()}, f)
    else:
        if not args.address:
            sys.exit("error: worker start needs --address <gcs>")
        gcs_address = args.address
    proc, info = start_node_daemon_process(
        gcs_address, num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        die_with_parent=False)
    print(f"node daemon {info['node_id'][:12]} at {info['address']} "
          f"(store {info['store_dir']})")
    print(f"join more nodes with: ray-tpu start --address {gcs_address}")
    print("processes run until killed (Ctrl-C detaches, does not stop them)")


def cmd_up(args) -> None:
    """Launch a cluster from a YAML config (ref: `ray up`,
    autoscaler/_private/commands.py create_or_update_cluster)."""
    if args.no_block:
        # The autoscaler must outlive this CLI process: run the blocking
        # launcher detached (its own session; `ray-tpu down` reaps it).
        from ray_tpu.autoscaler.launcher import spawn_detached_launcher

        address = spawn_detached_launcher(args.config)
        print(f"cluster up (detached launcher); connect with "
              f"ray_tpu.init(address={address!r})")
        return
    from ray_tpu.autoscaler.launcher import cluster_up

    cluster_up(args.config, block=True)


def cmd_down(args) -> None:
    """Tear down a launched cluster (ref: `ray down`)."""
    from ray_tpu.autoscaler.launcher import cluster_down

    cluster_down(args.config)


def cmd_lint(args) -> None:
    """Run the invariant lint suite; exits 0 clean / 1 violations /
    2 usage errors. Needs no cluster."""
    from ray_tpu.devtools.lint import (
        all_rules,
        default_root,
        render_text,
        run_lint,
        to_json,
    )

    root = args.root or default_root()
    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name:22s} {rule.description}")
        return
    if args.update_fingerprint:
        from ray_tpu.devtools.lint.rules.protocol_fingerprint import (
            update_fingerprint,
        )

        version, digest = update_fingerprint(root)
        print(f"recorded fingerprint {digest[:16]}… for "
              f"PROTOCOL_VERSION {version}")
        return
    if args.knob_table:
        from ray_tpu.devtools.lint.engine import LintContext
        from ray_tpu.devtools.lint.rules.knob_registry import (
            knob_table_markdown,
        )

        print(knob_table_markdown(LintContext(root)), end="")
        return
    try:
        violations, rules = run_lint(root, args.rules)
    except ValueError as e:
        sys.exit(f"error: {e}")
    if args.as_json:
        print(to_json(root, violations, rules))
    else:
        print(render_text(root, violations, rules))
    if violations:
        sys.exit(1)


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="ray-tpu")
    p.add_argument("--address", help="GCS address host:port")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    lp = sub.add_parser("list")
    lp.add_argument("kind", choices=["nodes", "actors", "tasks", "jobs",
                                     "pgs", "workers", "events"])
    lp.add_argument("--limit", type=int, default=200)
    tp = sub.add_parser("timeline")
    tp.add_argument("--out", default="timeline.json")
    tp.add_argument("--limit", type=int, default=10000)
    mp = sub.add_parser("metrics")
    mp.add_argument("--grafana-out", default=None,
                    help="write generated Grafana dashboards + "
                         "provisioning config to this dir and exit")
    mp.add_argument("--node", help="node id prefix filter")
    mp.add_argument("--federated", action="store_true",
                    help="print the GCS's merged, node-labelled "
                         "cluster exposition instead of per-daemon "
                         "scrapes")
    sp = sub.add_parser("start")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    jp = sub.add_parser("job")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    jps = jsub.add_parser("submit")
    jps.add_argument("entrypoint", nargs=argparse.REMAINDER)
    jps.add_argument("--submission-id", default=None)
    jps.add_argument("--wait", action="store_true")
    jps.add_argument("--timeout", type=float, default=600.0)
    for name in ("status", "logs", "stop"):
        jpx = jsub.add_parser(name)
        jpx.add_argument("submission_id")
    jsub.add_parser("list")
    svp = sub.add_parser(
        "serve", help="serving-plane observability: per-app latency/"
                      "KV rollup (status) and per-request span traces "
                      "(trace <request-id>)")
    ssub = svp.add_subparsers(dest="serve_cmd", required=True)
    ssub.add_parser("status")
    stp = ssub.add_parser("trace")
    stp.add_argument("request_id", help="request id (== trace id; the "
                                        "X-Request-Id header value)")
    stp.add_argument("-o", "--out", default=None,
                     help="output path (default trace-<id>.json)")
    tvp = sub.add_parser(
        "train", help="train-plane goodput observability: per-run "
                      "goodput split / step rate / cross-rank skew "
                      "(status) and per-rank step-phase span traces "
                      "(trace <run>)")
    tsub = tvp.add_subparsers(dest="train_cmd", required=True)
    tsub.add_parser("status")
    ttp = tsub.add_parser("trace")
    ttp.add_argument("run_id", help="run id (experiment name + fit "
                                    "attempt, e.g. 'mnist#0'; a bare "
                                    "experiment name matches every "
                                    "attempt)")
    ttp.add_argument("-o", "--out", default=None,
                     help="output path (default train-trace-<run>.json)")
    gcp = sub.add_parser(
        "gcs", help="GCS control-plane self-observability: per-service "
                    "x per-caller-component load shares, the event-loop "
                    "audit, and the slow-handler ring (gcs top)")
    gsub = gcp.add_subparsers(dest="gcs_cmd", required=True)
    gtp = gsub.add_parser("top")
    gtp.add_argument("--limit", type=int, default=20,
                     help="max (service, component) rows to print")
    ep = sub.add_parser(
        "events", help="cluster flight recorder: the durable journal of "
                       "state transitions (node join/death, failover, "
                       "drain + KV migration, resizes, PG repair)")
    ep.add_argument("--kind", help="kind prefix filter (e.g. 'node', "
                                   "'serve', 'pg.repair')")
    ep.add_argument("--node", help="exact node id filter")
    ep.add_argument("--since-s", type=float, default=None, dest="since_s",
                    help="only entries younger than this many seconds")
    ep.add_argument("--limit", type=int, default=50)
    sub.add_parser(
        "doctor", help="fused cluster health report: ranked findings "
                       "over federated metrics, hung tasks, event loss, "
                       "GCS load shares, loop lag, and the flight "
                       "recorder")
    dp = sub.add_parser("dashboard")
    dp.add_argument("--host", default="127.0.0.1")
    dp.add_argument("--port", type=int, default=8265)
    kp = sub.add_parser(
        "stack",
        help="signal-safe all-thread stack dumps from live workers "
             "(works on GIL-wedged workers; ref: `ray stack`)")
    kp.add_argument("--node", help="node id prefix filter")
    kp.add_argument("--worker", help="worker id prefix or exact pid")
    kp.add_argument("--task",
                    help="task id/name substring: dump only workers "
                         "running matching RUNNING attempts")
    kp.add_argument("--raw", action="store_true",
                    help="print raw faulthandler text instead of "
                         "parsed frames")
    kp.add_argument("--out", help="write the full dump JSON here")
    tp2 = sub.add_parser(
        "top", help="per-task resource usage (cpu/rss attribution "
                    "from task events)")
    tp2.add_argument("--node", help="node id prefix filter")
    tp2.add_argument("--per-node", action="store_true",
                     help="break rows out per node instead of "
                          "cluster-wide per name")
    tp2.add_argument("--limit", type=int, default=10000)
    pp = sub.add_parser(
        "profile",
        help="sampling cluster flamegraph: fan the profile RPC out to "
             "matching workers, merge collapsed stacks")
    pp.add_argument("--node", help="node id prefix filter")
    pp.add_argument("--worker", help="worker id prefix or exact pid")
    pp.add_argument("--task",
                    help="task id/name substring: profile only workers "
                         "running matching RUNNING attempts")
    pp.add_argument("--actor", help="actor id prefix filter")
    pp.add_argument("-d", "--duration", type=float, default=5.0)
    pp.add_argument("--interval", type=float, default=0.01)
    pp.add_argument("--out", default="cluster_flame.collapsed",
                    help="merged collapsed-stack output file")
    up = sub.add_parser("up")
    up.add_argument("config", help="cluster YAML path")
    up.add_argument("--no-block", action="store_true",
                    help="return after startup; the autoscaler runs in a "
                         "detached launcher process (`ray-tpu down` "
                         "stops it)")
    dn = sub.add_parser("down")
    dn.add_argument("config", help="cluster YAML path or cluster name")
    ln = sub.add_parser(
        "lint",
        help="run the AST invariant lint suite (knob registry, wire-typed "
             "errors, protocol fingerprint, async hot paths, lock order, "
             "reserved kwargs) over the source tree")
    ln.add_argument("--rule", action="append", dest="rules", metavar="NAME",
                    help="run only this rule (repeatable)")
    ln.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ln.add_argument("--root", default=None,
                    help="tree to lint (default: the installed package's "
                         "repo root)")
    ln.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ln.add_argument("--update-fingerprint", action="store_true",
                    help="record the current frame-layout hash for the "
                         "current PROTOCOL_VERSION and exit")
    ln.add_argument("--knob-table", action="store_true",
                    help="print the README knob table generated from the "
                         "config registry and exit")
    gp = sub.add_parser("logs")
    gp.add_argument("--node", help="node id prefix filter")
    gp.add_argument("--worker", help="worker id prefix filter")
    gp.add_argument("--actor", help="actor id prefix filter")
    gp.add_argument("--job", help="exact job id filter")
    gp.add_argument("--lines", type=int, default=100)
    gp.add_argument("--follow", action="store_true",
                    help="stream live lines instead of dumping buffers")
    gp.add_argument("--dead", nargs="?", const="", default=None,
                    metavar="WORKER",
                    help="post-mortem: only workers no longer alive "
                         "(optionally a worker id prefix) — their last "
                         "lines are retained GCS-side")
    args = p.parse_args(argv)

    if args.cmd == "lint":
        cmd_lint(args)
        return
    if args.cmd == "up":
        cmd_up(args)
        return
    if args.cmd == "down":
        cmd_down(args)
        return
    if args.cmd == "start":
        cmd_start(args)
        return
    if args.cmd == "job":
        cmd_job(args)
        return
    if args.cmd == "dashboard":
        cmd_dashboard(args)
        return
    if args.cmd == "metrics" and args.grafana_out:
        # Pure file generation — must work with NO cluster (falls back
        # to the known daemon metric set); uses live cluster metadata
        # when one is reachable.
        cmd_grafana_out(args)
        return
    gcs = _Gcs(_resolve_address(args))
    {"status": cmd_status, "list": cmd_list, "timeline": cmd_timeline,
     "metrics": cmd_metrics, "stack": cmd_stack, "top": cmd_top,
     "profile": cmd_profile, "logs": cmd_logs,
     "serve": cmd_serve, "train": cmd_train, "gcs": cmd_gcs,
     "events": cmd_events, "doctor": cmd_doctor}[args.cmd](gcs, args)


if __name__ == "__main__":
    main()
