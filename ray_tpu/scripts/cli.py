"""`ray-tpu` operator CLI.

ref: python/ray/scripts/scripts.py (click group :59 — ray start/status/
timeline/...) + the state CLI (python/ray/util/state/state_cli.py —
`ray list tasks|actors|nodes`). Subcommands talk straight to the GCS over
the pickle-codec RPC; the address comes from --address, RAY_TPU_ADDRESS,
or the breadcrumb the last local driver wrote.

Usage:
    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli list nodes|actors|tasks|jobs|pgs|workers
    python -m ray_tpu.scripts.cli timeline --out trace.json
    python -m ray_tpu.scripts.cli metrics [--node <id-prefix>]
    python -m ray_tpu.scripts.cli start --head [--num-cpus N ...]
    python -m ray_tpu.scripts.cli start --address <gcs> [--num-cpus N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

BREADCRUMB = f"/tmp/ray_tpu_{os.getuid()}/last_cluster.json"


def _resolve_address(args) -> str:
    if args.address:
        return args.address
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(BREADCRUMB) as f:
            return json.load(f)["gcs_address"]
    except (OSError, KeyError, ValueError):
        pass
    sys.exit("error: no cluster address (use --address, RAY_TPU_ADDRESS, "
             "or run a driver on this host first)")


class _Gcs:
    def __init__(self, address: str):
        from ray_tpu.core.distributed.rpc import (
            EventLoopThread,
            SyncRpcClient,
        )

        self._loop = EventLoopThread("cli")
        self.client = SyncRpcClient(address, self._loop)
        self.address = address

    def call(self, service, method, **kw):
        return self.client.call(service, method, timeout=15, **kw)

    def daemon(self, address: str):
        from ray_tpu.core.distributed.rpc import SyncRpcClient

        return SyncRpcClient(address, self._loop)


def _fmt_table(rows: List[List[str]], headers: List[str]) -> str:
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(str(c)))
    line = "  ".join(h.ljust(w) for h, w in zip(headers, widths))
    out = [line, "-" * len(line)]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------

def cmd_status(gcs: _Gcs, args) -> None:
    nodes = gcs.call("NodeInfo", "list_nodes")
    alive = [n for n in nodes if n["alive"]]
    total: dict = {}
    avail: dict = {}
    for n in alive:
        for k, v in n["total"].items():
            total[k] = total.get(k, 0) + v
        for k, v in n["available"].items():
            avail[k] = avail.get(k, 0) + v
    actors = gcs.call("ActorManager", "list_actors")
    jobs = gcs.call("JobManager", "list_jobs")
    pgs = gcs.call("PlacementGroups", "list_pgs")
    print(f"cluster @ {gcs.address}")
    print(f"  nodes: {len(alive)} alive / {len(nodes)} total")
    for k in sorted(total):
        if k == "memory":
            print(f"  memory: {avail.get(k, 0) / 1e9:.1f}/"
                  f"{total[k] / 1e9:.1f} GB free")
        else:
            print(f"  {k}: {avail.get(k, 0):g}/{total[k]:g} free")
    states = {}
    for a in actors:
        states[a["state"]] = states.get(a["state"], 0) + 1
    print(f"  actors: {len(actors)} "
          f"({', '.join(f'{k}={v}' for k, v in sorted(states.items()))})"
          if actors else "  actors: 0")
    print(f"  placement groups: {len(pgs)}")
    running = [j for j in jobs if not j.get("finished")]
    print(f"  jobs: {len(running)} running / {len(jobs)} total")
    # Observability rollup: task-event completeness + federation health.
    try:
        obs = gcs.call("Metrics", "cluster_summary")
    except Exception:  # noqa: BLE001 — pre-federation GCS
        return
    te = obs.get("task_events", {})
    dropped = (te.get("worker_dropped_status", 0)
               + te.get("worker_dropped_profile", 0))
    print(f"  task events: {te.get('stored', 0)} stored "
          f"({te.get('evicted', 0)} evicted, {dropped} dropped, "
          f"{te.get('gc_events', 0)} gc'd)")
    m = obs.get("metrics", {})
    staleness = m.get("staleness_s", {})
    worst = max(staleness.values(), default=0.0)
    print(f"  metrics federation: {m.get('nodes_reporting', 0)} nodes "
          f"reporting (worst staleness {worst:.1f}s)")


def cmd_list(gcs: _Gcs, args) -> None:
    kind = args.kind
    if kind == "nodes":
        rows = [[n["node_id"][:12], "ALIVE" if n["alive"] else "DEAD",
                 n["address"],
                 " ".join(f"{k}={v:g}" for k, v in sorted(
                     n["total"].items()) if k != "memory")]
                for n in gcs.call("NodeInfo", "list_nodes")]
        print(_fmt_table(rows, ["NODE_ID", "STATE", "ADDRESS", "RESOURCES"]))
    elif kind == "actors":
        rows = [[a["actor_id"][:12], a.get("cls_name", ""), a["state"],
                 a.get("name") or "", (a.get("node_id") or "")[:12]]
                for a in gcs.call("ActorManager", "list_actors")]
        print(_fmt_table(rows, ["ACTOR_ID", "CLASS", "STATE", "NAME",
                                "NODE"]))
    elif kind == "tasks":
        events = gcs.call("TaskEvents", "list_events", limit=args.limit)
        rows = [[e["task_id"][:12], e.get("name", ""), e.get("state", ""),
                 f"{(e.get('end_ts', 0) - e.get('start_ts', 0)) * 1000:.1f}",
                 (e.get("node_id") or "")[:12], e.get("error") or ""]
                for e in events]
        print(_fmt_table(rows, ["TASK_ID", "NAME", "STATE", "MS", "NODE",
                                "ERROR"]))
    elif kind == "jobs":
        rows = [[j["job_id"], "FINISHED" if j.get("finished") else "RUNNING",
                 time.strftime("%H:%M:%S",
                               time.localtime(j.get("start_time", 0)))]
                for j in gcs.call("JobManager", "list_jobs")]
        print(_fmt_table(rows, ["JOB_ID", "STATE", "STARTED"]))
    elif kind == "pgs":
        rows = [[p["pg_id"][:12], p["state"], p["strategy"],
                 str(len(p.get("bundles", [])))]
                for p in gcs.call("PlacementGroups", "list_pgs")]
        print(_fmt_table(rows, ["PG_ID", "STATE", "STRATEGY", "BUNDLES"]))
    elif kind == "events":
        import datetime

        rows = [[datetime.datetime.fromtimestamp(e["ts"]).strftime(
                     "%H:%M:%S"),
                 e["source"], e["severity"], e["message"]]
                for e in gcs.call("EventLog", "list_events",
                                  limit=args.limit)]
        print(_fmt_table(rows, ["TIME", "SOURCE", "SEVERITY", "MESSAGE"]))
    elif kind == "workers":
        rows = []
        for n in gcs.call("NodeInfo", "list_nodes"):
            if not n["alive"]:
                continue
            try:
                for w in gcs.daemon(n["address"]).call(
                        "NodeDaemon", "list_workers", timeout=10):
                    rows.append([n["node_id"][:12], w["worker_id"][:12],
                                 w["pid"],
                                 "actor" if w["actor_id"] else "task",
                                 "busy" if w["busy"] else "idle"])
            except Exception as e:  # noqa: BLE001
                rows.append([n["node_id"][:12], f"<unreachable: {e}>",
                             "", "", ""])
        print(_fmt_table(rows, ["NODE", "WORKER_ID", "PID", "KIND",
                                "STATE"]))


def cmd_timeline(gcs: _Gcs, args) -> None:
    from ray_tpu.util.timeline import chrome_trace

    events = gcs.call("TaskEvents", "list_events", limit=args.limit)
    with open(args.out, "w") as f:
        json.dump(chrome_trace(events), f)
    print(f"wrote {len(events)} events to {args.out} "
          f"(open in chrome://tracing)")


def cmd_grafana_out(args) -> None:
    """Generate importable Grafana dashboards + provisioning config
    (ref: grafana_dashboard_factory.py). Metric metadata comes from a
    live node's Prometheus dump when a cluster is reachable, else from
    the known daemon metric set — so this works air-gapped."""
    from ray_tpu.dashboard.grafana import (
        metrics_from_prometheus_text,
        write_dashboards,
    )

    metrics = None
    try:
        gcs = _Gcs(_resolve_address(args))
        for n in gcs.call("NodeInfo", "list_nodes"):
            if not n["alive"]:
                continue
            text = gcs.daemon(n["address"]).call(
                "NodeDaemon", "get_metrics", timeout=10)
            metrics = metrics_from_prometheus_text(text)
            break
    except Exception:  # noqa: BLE001 — no cluster: static fallback
        pass
    for path in write_dashboards(args.grafana_out, metrics=metrics):
        print(path)


def cmd_metrics(gcs: _Gcs, args) -> None:
    if getattr(args, "federated", False):
        # One exposition for the whole cluster, node-labelled, straight
        # from the GCS's syncer-fed federation cache — no per-daemon
        # scrape fan-out.
        print(gcs.call("Metrics", "federated_text"))
        return
    for n in gcs.call("NodeInfo", "list_nodes"):
        if not n["alive"]:
            continue
        if args.node and not n["node_id"].startswith(args.node):
            continue
        print(f"# node {n['node_id'][:12]} @ {n['address']}")
        try:
            print(gcs.daemon(n["address"]).call("NodeDaemon", "get_metrics",
                                                timeout=10))
        except Exception as e:  # noqa: BLE001
            print(f"# unreachable: {e}")


def cmd_job(args) -> None:
    """Job submission commands (ref: `ray job submit/status/logs/stop/list`,
    dashboard/modules/job/cli.py). Uses the direct-to-cluster client."""
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    if args.job_cmd == "submit":
        import shlex

        words = args.entrypoint
        if words and words[0] == "--":
            words = words[1:]
        # shlex.join keeps argument boundaries (a bare " ".join would let
        # the shell re-split/interpret `-c "print(1)"`).
        sid = client.submit_job(entrypoint=shlex.join(words),
                                submission_id=args.submission_id)
        print(f"submitted job {sid}")
        if args.wait:
            info = client.wait_until_finished(sid, timeout=args.timeout)
            print(client.get_job_logs(sid), end="")
            print(f"job {sid}: {info.status} {info.message}")
            if info.status != "SUCCEEDED":
                sys.exit(1)
    elif args.job_cmd == "status":
        info = client.get_job_info(args.submission_id)
        print(f"{info.submission_id}: {info.status} {info.message}")
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_cmd == "stop":
        ok = client.stop_job(args.submission_id)
        print("stopped" if ok else "not running")
    elif args.job_cmd == "list":
        rows = [[j.submission_id, j.status,
                 time.strftime("%H:%M:%S",
                               time.localtime(j.start_time or 0)),
                 j.entrypoint[:60]]
                for j in client.list_jobs()]
        print(_fmt_table(rows, ["SUBMISSION_ID", "STATUS", "STARTED",
                                "ENTRYPOINT"]))


def cmd_stack(gcs: _Gcs, args) -> None:
    """Sample a live worker's stacks (ref: `ray stack` / dashboard
    py-spy profiling). Target by worker-id prefix, or omit to sample
    every worker on every node."""
    from ray_tpu.util.profiling import render_report

    for n in gcs.call("NodeInfo", "list_nodes"):
        if not n["alive"]:
            continue
        try:
            workers = gcs.daemon(n["address"]).call(
                "NodeDaemon", "list_workers", timeout=10)
        except Exception:  # noqa: BLE001
            continue
        for w in workers:
            if args.worker and not w["worker_id"].startswith(args.worker):
                continue
            if not w.get("address"):
                continue
            print(f"== worker {w['worker_id'][:12]} pid={w['pid']} "
                  f"on node {n['node_id'][:12]}")
            try:
                report = gcs.daemon(w["address"]).call(
                    "Worker", "profile", duration_s=args.duration,
                    timeout=args.duration + 30)
                print(render_report(report))
                if args.out:
                    from ray_tpu.util.profiling import (
                        write_flamegraph_collapsed,
                    )

                    path = f"{args.out}.{w['worker_id'][:12]}.collapsed"
                    write_flamegraph_collapsed(report, path)
                    print(f"collapsed stacks -> {path}")
            except Exception as e:  # noqa: BLE001
                print(f"  <unreachable: {e}>")


def cmd_logs(gcs: _Gcs, args) -> None:
    """Worker log access (ref: `ray logs` CLI, log_monitor tailing):
    dumps the GCS ring buffers (works for DEAD workers too), or streams
    the live pubsub channel with --follow."""
    if args.follow:
        import asyncio

        from ray_tpu.core.distributed.log_monitor import format_log_prefix
        from ray_tpu.core.distributed.rpc import AsyncRpcClient

        async def follow():
            client = AsyncRpcClient(gcs.address)
            try:
                async for rec in client.stream(
                        "Pubsub", "stream_subscribe", channel="logs"):
                    if args.node and not rec["node_id"].startswith(
                            args.node):
                        continue
                    if args.worker and not rec["worker_id"].startswith(
                            args.worker):
                        continue
                    if args.actor and not (rec.get("actor_id")
                                           or "").startswith(args.actor):
                        continue
                    if args.job and rec.get("job_id") != args.job:
                        continue
                    prefix = format_log_prefix(rec)
                    for line in rec["lines"]:
                        print(f"{prefix} {line}", flush=True)
            finally:
                await client.close()

        try:
            asyncio.run(follow())
        except KeyboardInterrupt:
            pass
        return
    records = gcs.call("LogManager", "tail_logs", node_id=args.node,
                       worker_id=args.worker, actor_id=args.actor,
                       job_id=args.job, num_lines=args.lines)
    for rec in sorted(records, key=lambda r: (r["node_id"],
                                              r["worker_id"])):
        who = (f"actor={rec['actor_id'][:12]}" if rec.get("actor_id")
               else f"worker={rec['worker_id'][:12]}")
        print(f"== {who} node={rec['node_id'][:12]} [{rec['stream']}]")
        for line in rec["lines"]:
            print(f"  {line}")


def cmd_dashboard(args) -> None:
    """Serve the web dashboard for a running cluster (ref: `ray
    dashboard`, dashboard/head.py)."""
    import asyncio

    from ray_tpu.dashboard.head import DashboardHead

    address = _resolve_address(args)

    async def run():
        head = DashboardHead(address, args.host, args.port)
        port = await head.start()
        print(f"dashboard at http://{args.host}:{port} (Ctrl-C to stop)")
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


def cmd_start(args) -> None:
    """Start a head (GCS + daemon) or join a worker daemon to a cluster
    (ref: `ray start --head` / `ray start --address=...`)."""
    from ray_tpu.core.distributed.driver import (
        start_gcs_process,
        start_node_daemon_process,
    )

    if args.head:
        gcs_proc, gcs_address = start_gcs_process(die_with_parent=False)
        print(f"GCS started at {gcs_address}")
        os.makedirs(os.path.dirname(BREADCRUMB), mode=0o700, exist_ok=True)
        with open(BREADCRUMB, "w") as f:
            json.dump({"gcs_address": gcs_address, "ts": time.time()}, f)
    else:
        if not args.address:
            sys.exit("error: worker start needs --address <gcs>")
        gcs_address = args.address
    proc, info = start_node_daemon_process(
        gcs_address, num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        die_with_parent=False)
    print(f"node daemon {info['node_id'][:12]} at {info['address']} "
          f"(store {info['store_dir']})")
    print(f"join more nodes with: ray-tpu start --address {gcs_address}")
    print("processes run until killed (Ctrl-C detaches, does not stop them)")


def cmd_up(args) -> None:
    """Launch a cluster from a YAML config (ref: `ray up`,
    autoscaler/_private/commands.py create_or_update_cluster)."""
    if args.no_block:
        # The autoscaler must outlive this CLI process: run the blocking
        # launcher detached (its own session; `ray-tpu down` reaps it).
        from ray_tpu.autoscaler.launcher import spawn_detached_launcher

        address = spawn_detached_launcher(args.config)
        print(f"cluster up (detached launcher); connect with "
              f"ray_tpu.init(address={address!r})")
        return
    from ray_tpu.autoscaler.launcher import cluster_up

    cluster_up(args.config, block=True)


def cmd_down(args) -> None:
    """Tear down a launched cluster (ref: `ray down`)."""
    from ray_tpu.autoscaler.launcher import cluster_down

    cluster_down(args.config)


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="ray-tpu")
    p.add_argument("--address", help="GCS address host:port")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    lp = sub.add_parser("list")
    lp.add_argument("kind", choices=["nodes", "actors", "tasks", "jobs",
                                     "pgs", "workers", "events"])
    lp.add_argument("--limit", type=int, default=200)
    tp = sub.add_parser("timeline")
    tp.add_argument("--out", default="timeline.json")
    tp.add_argument("--limit", type=int, default=10000)
    mp = sub.add_parser("metrics")
    mp.add_argument("--grafana-out", default=None,
                    help="write generated Grafana dashboards + "
                         "provisioning config to this dir and exit")
    mp.add_argument("--node", help="node id prefix filter")
    mp.add_argument("--federated", action="store_true",
                    help="print the GCS's merged, node-labelled "
                         "cluster exposition instead of per-daemon "
                         "scrapes")
    sp = sub.add_parser("start")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    jp = sub.add_parser("job")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    jps = jsub.add_parser("submit")
    jps.add_argument("entrypoint", nargs=argparse.REMAINDER)
    jps.add_argument("--submission-id", default=None)
    jps.add_argument("--wait", action="store_true")
    jps.add_argument("--timeout", type=float, default=600.0)
    for name in ("status", "logs", "stop"):
        jpx = jsub.add_parser(name)
        jpx.add_argument("submission_id")
    jsub.add_parser("list")
    dp = sub.add_parser("dashboard")
    dp.add_argument("--host", default="127.0.0.1")
    dp.add_argument("--port", type=int, default=8265)
    kp = sub.add_parser("stack")
    kp.add_argument("--worker", help="worker id prefix filter")
    kp.add_argument("--duration", type=float, default=2.0)
    kp.add_argument("--out", help="write collapsed flamegraph stacks")
    up = sub.add_parser("up")
    up.add_argument("config", help="cluster YAML path")
    up.add_argument("--no-block", action="store_true",
                    help="return after startup; the autoscaler runs in a "
                         "detached launcher process (`ray-tpu down` "
                         "stops it)")
    dn = sub.add_parser("down")
    dn.add_argument("config", help="cluster YAML path or cluster name")
    gp = sub.add_parser("logs")
    gp.add_argument("--node", help="node id prefix filter")
    gp.add_argument("--worker", help="worker id prefix filter")
    gp.add_argument("--actor", help="actor id prefix filter")
    gp.add_argument("--job", help="exact job id filter")
    gp.add_argument("--lines", type=int, default=100)
    gp.add_argument("--follow", action="store_true",
                    help="stream live lines instead of dumping buffers")
    args = p.parse_args(argv)

    if args.cmd == "up":
        cmd_up(args)
        return
    if args.cmd == "down":
        cmd_down(args)
        return
    if args.cmd == "start":
        cmd_start(args)
        return
    if args.cmd == "job":
        cmd_job(args)
        return
    if args.cmd == "dashboard":
        cmd_dashboard(args)
        return
    if args.cmd == "metrics" and args.grafana_out:
        # Pure file generation — must work with NO cluster (falls back
        # to the known daemon metric set); uses live cluster metadata
        # when one is reachable.
        cmd_grafana_out(args)
        return
    gcs = _Gcs(_resolve_address(args))
    {"status": cmd_status, "list": cmd_list, "timeline": cmd_timeline,
     "metrics": cmd_metrics, "stack": cmd_stack,
     "logs": cmd_logs}[args.cmd](gcs, args)


if __name__ == "__main__":
    main()
