"""Runtime context introspection (ref: python/ray/runtime_context.py)."""
from __future__ import annotations

import os
from typing import Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_node_id(self) -> str:
        return getattr(self._worker, "node_id", "local")

    def get_job_id(self) -> str:
        return getattr(self._worker, "job_id", "local")

    def get_worker_id(self) -> str:
        return getattr(self._worker, "address", "local")

    def get_pid(self) -> int:
        return os.getpid()

    def get_actor_id(self) -> Optional[str]:
        return getattr(self._worker, "current_actor_id", None)

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.api import _global_worker

    return RuntimeContext(_global_worker())
