"""AIR substrate: shared configs + the actor/resource execution layer
(ref: python/ray/air/ — config.py, execution/)."""
from ray_tpu.air.execution import RayActorManager, TrackedActor
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)

__all__ = [
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "RayActorManager",
    "TrackedActor",
]
