"""RayActorManager: event-based actor + actor-task management.

ref: python/ray/air/execution/_internal/actor_manager.py:23 (the event
manager Tune's controller runs on) and tracked_actor.py /
tracked_actor_task.py. Lean reimplementation over the ray_tpu runtime:
actors start asynchronously, tasks resolve through their futures, and
every outcome is delivered as a sequential callback inside `next()`.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

Callback = Optional[Callable[..., Any]]


def _ready_probe(_instance):
    """Module-level so plain pickle handles it (a lambda would force
    cloudpickle on every actor start)."""
    return True


class TrackedActor:
    """Handle for a manager-owned actor (ref: tracked_actor.py)."""

    __slots__ = ("actor_id", "_mgr")

    def __init__(self, actor_id: str, mgr: "RayActorManager"):
        self.actor_id = actor_id
        self._mgr = mgr

    @property
    def state(self) -> str:
        rec = self._mgr._actors.get(self.actor_id)
        return rec["state"] if rec else "REMOVED"

    def __repr__(self) -> str:
        return f"TrackedActor({self.actor_id[:8]}, {self.state})"


class RayActorManager:
    """Owns actor lifecycles + task futures; `next()` pumps events."""

    def __init__(self):
        self._actors: Dict[str, dict] = {}
        # (tracked, method, args, kwargs, on_result, on_error) futures.
        self._task_futs: List[Tuple[Any, dict]] = []
        self._pending_start: List[Tuple[Any, dict]] = []

    # -- queries --------------------------------------------------------
    @property
    def num_live_actors(self) -> int:
        return sum(1 for a in self._actors.values()
                   if a["state"] == "STARTED")

    @property
    def num_pending_actors(self) -> int:
        return sum(1 for a in self._actors.values()
                   if a["state"] == "PENDING")

    @property
    def num_pending_tasks(self) -> int:
        return len(self._task_futs)

    def live_actors(self) -> List[TrackedActor]:
        return [a["tracked"] for a in self._actors.values()
                if a["state"] == "STARTED"]

    # -- lifecycle ------------------------------------------------------
    def add_actor(self, cls, *, kwargs: Optional[dict] = None,
                  resources: Optional[Dict[str, float]] = None,
                  max_restarts: int = 0,
                  on_start: Callback = None, on_stop: Callback = None,
                  on_error: Callback = None) -> TrackedActor:
        """Request an actor. It starts asynchronously; `on_start(tracked)`
        fires from a later `next()` once its constructor completed."""
        import ray_tpu

        actor_id = uuid.uuid4().hex
        tracked = TrackedActor(actor_id, self)
        opts = {"num_cpus": (resources or {}).get("CPU", 0),
                "max_restarts": max_restarts}
        custom = {k: v for k, v in (resources or {}).items() if k != "CPU"}
        if custom:
            opts["resources"] = custom
        remote_cls = ray_tpu.remote(**opts)(cls)
        handle = remote_cls.remote(**(kwargs or {}))
        rec = {
            "tracked": tracked, "handle": handle, "state": "PENDING",
            "on_start": on_start, "on_stop": on_stop,
            "on_error": on_error,
        }
        self._actors[actor_id] = rec
        # Readiness probe (ref: the __ray_ready__ future): a no-op apply
        # through the actor's generic-call escape hatch — ActorHandle
        # hides dunder attributes, so go through ActorMethod directly.
        from ray_tpu.actor import ActorMethod

        ready_ref = ActorMethod(handle, "__raytpu_apply__").remote(
            _ready_probe)
        self._pending_start.append((ready_ref.future(), rec))
        return tracked

    def remove_actor(self, tracked: TrackedActor) -> None:
        """Stop an actor; `on_stop(tracked)` fires from a later next()."""
        import ray_tpu

        rec = self._actors.get(tracked.actor_id)
        if rec is None or rec["state"] in ("STOPPED", "FAILED"):
            return
        try:
            ray_tpu.kill(rec["handle"])
        except Exception:  # noqa: BLE001
            pass
        rec["state"] = "STOPPED"
        rec["_stop_pending"] = True

    # -- tasks ----------------------------------------------------------
    def schedule_actor_task(self, tracked: TrackedActor, method: str,
                            args: tuple = (), kwargs: Optional[dict] = None,
                            *, on_result: Callback = None,
                            on_error: Callback = None) -> None:
        """Invoke `method` on the actor; exactly one of on_result(tracked,
        result) / on_error(tracked, exception) fires from a later next()."""
        rec = self._actors.get(tracked.actor_id)
        if rec is None:
            raise ValueError("actor is not tracked (removed?)")
        ref = getattr(rec["handle"], method).remote(*args,
                                                    **(kwargs or {}))
        self._task_futs.append((ref.future(), {
            "tracked": tracked, "on_result": on_result,
            "on_error": on_error}))

    # -- event pump -----------------------------------------------------
    def next(self, timeout: Optional[float] = 1.0) -> bool:
        """Process the next ready event (actor started / stopped / task
        finished); returns True if an event was handled. Callbacks run
        HERE, sequentially — never from background threads."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._pump_stops():
                return True
            if self._pump_starts():
                return True
            if self._pump_tasks():
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def _pump_stops(self) -> bool:
        for rec in self._actors.values():
            if rec.pop("_stop_pending", False):
                if rec["on_stop"]:
                    rec["on_stop"](rec["tracked"])
                return True
        return False

    def _pump_starts(self) -> bool:
        for i, (fut, rec) in enumerate(self._pending_start):
            if not fut.done():
                continue
            del self._pending_start[i]
            if rec["state"] == "STOPPED":
                return True  # removed before start completed
            try:
                fut.result()
            except Exception as e:  # noqa: BLE001 constructor failed
                rec["state"] = "FAILED"
                if rec["on_error"]:
                    rec["on_error"](rec["tracked"], e)
                return True
            rec["state"] = "STARTED"
            if rec["on_start"]:
                rec["on_start"](rec["tracked"])
            return True
        return False

    def _pump_tasks(self) -> bool:
        for i, (fut, ctx) in enumerate(self._task_futs):
            if not fut.done():
                continue
            del self._task_futs[i]
            tracked = ctx["tracked"]
            try:
                result = fut.result()
            except Exception as e:  # noqa: BLE001
                from ray_tpu import exceptions as rexc

                # Only actor-death errors change the ACTOR's state; an
                # application exception is the task's problem alone.
                if isinstance(e, (rexc.ActorDiedError,
                                  rexc.ActorUnavailableError,
                                  rexc.WorkerCrashedError)):
                    rec = self._actors.get(tracked.actor_id)
                    if rec is not None and rec["state"] == "STARTED":
                        rec["state"] = "FAILED"
                        if rec["on_error"]:
                            rec["on_error"](tracked, e)
                if ctx["on_error"]:
                    ctx["on_error"](tracked, e)
                return True
            if ctx["on_result"]:
                ctx["on_result"](tracked, result)
            return True
        return False

    # -- teardown -------------------------------------------------------
    def shutdown(self) -> None:
        for rec in list(self._actors.values()):
            if rec["state"] in ("PENDING", "STARTED"):
                self.remove_actor(rec["tracked"])
        self._task_futs.clear()
        self._pending_start.clear()
