"""Event-driven actor execution layer shared by the AIR libraries.

ref: python/ray/air/execution/_internal/actor_manager.py:23
RayActorManager — the reference centralizes actor lifecycle + task
tracking for Tune/Train behind one event-based manager, so elastic
trials and failure handling live in ONE place instead of three bespoke
controllers. Same contract here:

    mgr = RayActorManager()
    tracked = mgr.add_actor(ActorClass, kwargs={...},
                            resources={"CPU": 1},
                            on_start=..., on_stop=..., on_error=...)
    mgr.schedule_actor_task(tracked, "step", on_result=..., on_error=...)
    while mgr.num_live_actors or mgr.num_pending_tasks:
        mgr.next(timeout=1.0)     # control is yielded explicitly;
                                  # callbacks run sequentially here

No background threads: `next()` drives everything (the reference makes
the same choice — deterministic callback ordering beats async fan-out
for a training control loop).
"""
from ray_tpu.air.execution.actor_manager import RayActorManager, TrackedActor

__all__ = ["RayActorManager", "TrackedActor"]
