"""Readers / from_* constructors (ref: python/ray/data/read_api.py —
read_parquet :604, read_images :775, from_huggingface :2663; datasource/).

Each reader pre-splits its source into `ReadTask`s (one block each) so the
streaming executor parallelizes and fuses downstream maps into the read.
"""
from __future__ import annotations

import functools
import glob as globlib
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data import block as B
from ray_tpu.data.dataset import Dataset, from_block_list
from ray_tpu.data.plan import ReadTask


def _expand_paths(paths, suffixes=None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if suffixes:
        out = [p for p in out
               if any(p.lower().endswith(s) for s in suffixes)]
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def _tasks_from_files(files: List[str], read_one, name: str) -> Dataset:
    return Dataset([ReadTask(functools.partial(read_one, f), name=name)
                    for f in files])


# ---------------- synthetic ----------------
def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    parallelism = max(1, min(parallelism, n or 1))
    per = -(-n // parallelism) if n else 0

    def make(start, end):
        return lambda: pa.table({"id": np.arange(start, end)})

    tasks = []
    i = 0
    while i * per < n:
        tasks.append(ReadTask(make(i * per, min((i + 1) * per, n)),
                              name="range"))
        i += 1
    if not tasks:
        tasks = [ReadTask(lambda: pa.table({"id": np.arange(0)}),
                          name="range")]
    return Dataset(tasks)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    base = range(n, parallelism=parallelism)

    def expand(batch):
        ids = batch["id"]
        data = np.broadcast_to(ids.reshape((-1,) + (1,) * len(shape)),
                               (len(ids),) + tuple(shape)).copy()
        return {"data": data}

    return base.map_batches(expand, batch_format="numpy")


# ---------------- from_* ----------------
def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    if not items:
        return from_block_list([pa.table({})])
    parallelism = max(1, min(parallelism, len(items)))
    per = -(-len(items) // parallelism)
    blocks = [B.from_rows(items[i:i + per])
              for i in __import__("builtins").range(0, len(items), per)]
    return from_block_list(blocks)


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    return from_block_list([B.from_batch({column: arr})])


def from_arrow(table: pa.Table) -> Dataset:
    return from_block_list([table])


def from_pandas(df) -> Dataset:
    return from_block_list([pa.Table.from_pandas(df, preserve_index=False)])


def from_huggingface(hf_dataset) -> Dataset:
    """HF datasets are Arrow-backed; grab the table directly."""
    t = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if t is None:
        t = pa.Table.from_pydict(hf_dataset.to_dict())
    return from_block_list([t.combine_chunks()])


def from_torch(torch_dataset) -> Dataset:
    return from_items([torch_dataset[i]
                       for i in __import__("builtins").range(
                           len(torch_dataset))])


# ---------------- file formats ----------------
def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 **_kw) -> Dataset:
    files = _expand_paths(paths, (".parquet", ".pq"))

    def read_one(f):
        import pyarrow.parquet as pq

        return pq.read_table(f, columns=columns)

    return _tasks_from_files(files, read_one, "read_parquet")


def read_csv(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".csv",))

    def read_one(f):
        import pyarrow.csv as pcsv

        return pcsv.read_csv(f)

    return _tasks_from_files(files, read_one, "read_csv")


def read_json(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".json", ".jsonl"))

    def read_one(f):
        import pyarrow.json as pjson

        return pjson.read_json(f)

    return _tasks_from_files(files, read_one, "read_json")


def read_text(paths, **_kw) -> Dataset:
    files = _expand_paths(paths)

    def read_one(f):
        with open(f, encoding="utf-8") as fh:
            lines = [ln.rstrip("\n") for ln in fh]
        return pa.table({"text": lines})

    return _tasks_from_files(files, read_one, "read_text")


def read_binary_files(paths, *, include_paths: bool = False, **_kw) -> Dataset:
    files = _expand_paths(paths)

    def read_one(f):
        with open(f, "rb") as fh:
            data = fh.read()
        cols = {"bytes": [data]}
        if include_paths:
            cols["path"] = [f]
        return pa.table(cols)

    return _tasks_from_files(files, read_one, "read_binary")


def read_images(paths, *, size: Optional[tuple] = None,
                mode: str = "RGB", include_paths: bool = False,
                **_kw) -> Dataset:
    """Decode images into a tensor column (ref: read_api.py:775
    read_images + datasource/image_datasource.py)."""
    files = _expand_paths(paths, (".png", ".jpg", ".jpeg", ".bmp", ".gif",
                                  ".webp"))

    def read_one(f):
        from PIL import Image

        img = Image.open(f).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arr = np.asarray(img)[None]  # (1, H, W, C)
        batch: Dict[str, Any] = {"image": arr}
        t = B.from_batch(batch)
        if include_paths:
            t = t.append_column("path", pa.array([f]))
        return t

    return _tasks_from_files(files, read_one, "read_images")


def read_numpy(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".npy",))

    def read_one(f):
        return B.from_batch({"data": np.load(f)})

    return _tasks_from_files(files, read_one, "read_numpy")


def read_tfrecords(paths, **_kw) -> Dataset:
    """TFRecord files of tf.train.Example protos (ref: datasource/
    tfrecords_datasource.py) — decoded by the built-in codec, no
    tensorflow needed."""
    files = _expand_paths(paths, (".tfrecords", ".tfrecord"))

    def read_one(f):
        from ray_tpu.data import tfrecord

        rows = [tfrecord.decode_example(p)
                for p in tfrecord.read_records(f)]
        if not rows:
            return pa.table({})
        return B.from_rows(rows)

    return _tasks_from_files(files, read_one, "read_tfrecords")


def read_webdataset(paths, *, decode: bool = True, **_kw) -> Dataset:
    """WebDataset tar shards: members named <key>.<ext> group into one
    sample per key (ref: datasource/webdataset_datasource.py). Known
    extensions decode (json/txt/cls/npy); everything else stays bytes."""
    files = _expand_paths(paths, (".tar",))

    def read_one(f):
        import io
        import json as jsonlib
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(f) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                if "." not in base:
                    continue
                key, ext = base.split(".", 1)
                data = tar.extractfile(member).read()
                if decode:
                    if ext == "json":
                        data = jsonlib.loads(data)
                    elif ext in ("txt", "text"):
                        data = data.decode()
                    elif ext == "cls":
                        data = int(data)
                    elif ext == "npy":
                        data = np.load(io.BytesIO(data))
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = data
        rows = [samples[k] for k in order]
        if not rows:
            return pa.table({})
        # Shards routinely have optional fields: normalize to the union
        # of keys (missing -> None) or column construction KeyErrors.
        all_keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in all_keys:
                    all_keys.append(k)
        rows = [{k: r.get(k) for k in all_keys} for r in rows]
        return B.from_rows(rows)

    return _tasks_from_files(files, read_one, "read_webdataset")


def read_sql(sql: str, connection_factory, **_kw) -> Dataset:
    """Run a query through a DBAPI connection factory (ref: datasource/
    sql_datasource.py — e.g. `lambda: sqlite3.connect(path)`). The query
    executes inside one read task on the cluster (arbitrary SQL cannot
    be partitioned generically; shard by issuing multiple queries)."""

    def read_one(_unused=None):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        if not rows:
            return pa.table({c: [] for c in cols})
        return pa.table({c: [r[i] for r in rows]
                         for i, c in enumerate(cols)})

    return Dataset([ReadTask(read_one, name="read_sql")])


def read_mongo(uri: Optional[str] = None, database: Optional[str] = None,
               collection: Optional[str] = None, *,
               pipeline: Optional[List[dict]] = None,
               pipelines: Optional[List[List[dict]]] = None,
               client_factory=None, **_kw) -> Dataset:
    """Read a MongoDB collection (ref: datasource/mongo_datasource.py).

    Positional shape matches the reference: (uri, database, collection).
    `client_factory` is the injectable seam (same idiom as `read_sql`'s
    connection_factory and the GCP provider transport): any callable
    returning a pymongo.MongoClient-compatible object — tests inject a
    fake, production omits it and pymongo connects to `uri`. Pass
    `pipelines` (a list of aggregation pipelines) to shard the read
    into one task per pipeline; `pipeline` alone reads in one task."""
    if not database or not collection:
        raise ValueError("read_mongo needs `database` and `collection`")
    if client_factory is None:
        def client_factory():  # pragma: no cover - needs a live mongod
            try:
                import pymongo
            except ImportError as e:
                raise ImportError(
                    "read_mongo needs `pymongo` (or pass "
                    "client_factory=)") from e
            return pymongo.MongoClient(uri)

    shards = pipelines if pipelines is not None else [pipeline or []]

    def make_read(shard_pipeline):
        def read_one(_unused=None):
            client = client_factory()
            try:
                coll = client[database][collection]
                rows = [dict(doc) for doc in
                        (coll.aggregate(shard_pipeline)
                         if shard_pipeline else coll.find())]
            finally:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass
            for r in rows:
                r.pop("_id", None)   # ObjectId is not arrow-encodable
            if not rows:
                return pa.table({})
            return B.from_rows(rows)

        return read_one

    return Dataset([ReadTask(make_read(p), name="read_mongo")
                    for p in shards])


def read_bigquery(project_id: Optional[str] = None, *,
                  dataset: Optional[str] = None,
                  query: Optional[str] = None,
                  client_factory=None, **_kw) -> Dataset:
    """Read a BigQuery table or query result (ref: datasource/
    bigquery_datasource.py — same (project_id, dataset=, query=) shape
    as the reference's read_bigquery). `client_factory` returns a
    google.cloud.bigquery.Client-compatible object (tests inject a
    fake); `dataset` is "dataset.table" when `query` is None."""
    if query is None:
        if not dataset:
            raise ValueError("read_bigquery needs `query` or `dataset`")
        query = f"SELECT * FROM `{dataset}`"

    if client_factory is None:
        def client_factory():  # pragma: no cover - needs GCP creds
            try:
                from google.cloud import bigquery
            except ImportError as e:
                raise ImportError(
                    "read_bigquery needs `google-cloud-bigquery` (or "
                    "pass client_factory=)") from e
            return bigquery.Client(project=project_id)

    def read_one(_unused=None):
        client = client_factory()
        result = client.query(query).result()
        to_arrow = getattr(result, "to_arrow", None)
        if to_arrow is not None:
            return to_arrow()
        rows = [dict(r.items()) if hasattr(r, "items") else dict(r)
                for r in result]
        if not rows:
            return pa.table({})
        return B.from_rows(rows)

    return Dataset([ReadTask(read_one, name="read_bigquery")])
