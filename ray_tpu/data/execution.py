"""Operator-graph streaming executor.

The reference runs each dataset as a graph of concurrent operators with
per-operator resource budgets, a scheduling step that picks which
operator to advance, and pluggable backpressure
(ref: python/ray/data/_internal/execution/streaming_executor.py:55,
streaming_executor_state.py:494 `select_operator_to_run`,
backpressure_policy/). This module is the equivalent:

- Each map segment becomes a linear graph of operators (a read source,
  fused task-map operators, actor-pool operators). Every operator owns a
  BOUNDED input queue, an in-flight task budget, and a bounded output
  queue.
- A scheduling step harvests completions, propagates blocks between
  queues, then advances the RUNNABLE operator with the most headroom
  (free budget fraction; ties drain downstream-most first) — one task
  per step, so all operators genuinely overlap instead of running as
  chained sliding windows.
- Backpressure composes three ways: the in-flight budget (shrunk under
  object-store pressure, ref: concurrency_cap/streaming_output
  backpressure policies), the bounded inter-operator queues, and the
  consumer itself — the executor is a generator, so when the caller
  stops pulling, scheduling pauses.

Blocks stay ordered (completions are harvested in submission order per
operator), matching the reference's default preserve_order=False cost
model conservatively. All-to-all stages remain barriers between
segments, as in the reference's plan segmentation.
"""
from __future__ import annotations

import logging
from collections import deque
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.block import Block, concat
from ray_tpu.data.plan import AllToAllStage, MapStage, ReadTask, fuse_map_chain
from ray_tpu.data.stats import DatasetStats, StageStats

logger = logging.getLogger(__name__)

DEFAULT_MAX_IN_FLIGHT = 16


def _default_window() -> int:
    """Resource-aware per-operator budget (ref: backpressure_policy/
    concurrency_cap_backpressure_policy.py): enough in-flight tasks to
    cover the cluster's CPUs twice, bounded."""
    try:
        cpus = int(ray_tpu.cluster_resources().get("CPU", 4))
    except Exception:  # noqa: BLE001
        cpus = 4
    return max(4, min(2 * cpus, 64))


def _effective_window(base: int) -> int:
    """Shrink a budget under object-store pressure (ref:
    backpressure_policy/streaming_output_backpressure_policy.py — the
    executor must not outrun consumers into an overflowing store)."""
    try:
        from ray_tpu.api import _global_worker

        store = _global_worker().store
        cap = getattr(store, "capacity", 0)
        if cap and store.used / cap > 0.85:
            return max(2, base // 4)
    except Exception:  # noqa: BLE001
        pass
    return base


def _run_read(read_fn, map_fn) -> Block:
    blocks = [read_fn()]
    if map_fn is not None:
        out: List[Block] = []
        for b in blocks:
            out.extend(map_fn(b))
        blocks = out
    return concat(blocks) if len(blocks) != 1 else blocks[0]


def _run_map(block: Block, map_fn) -> Block:
    out = list(map_fn(block))
    return concat(out) if len(out) != 1 else out[0]


class _ActorPool:
    """Small pool of UDF-holding actors with least-loaded dispatch
    (ref: execution/operators/actor_pool_map_operator.py)."""

    def __init__(self, fn_maker, size: int):
        @ray_tpu.remote
        class _MapActor:
            def __init__(self, maker):
                self._fn = maker()

            def apply(self, block):
                out = list(self._fn(block))
                return concat(out) if len(out) != 1 else out[0]

        self.actors = [_MapActor.remote(fn_maker) for _ in range(size)]
        self.load = [0] * size

    def submit(self, block_ref):
        i = min(range(len(self.actors)), key=lambda j: self.load[j])
        self.load[i] += 1
        ref = self.actors[i].apply.remote(block_ref)
        return i, ref

    def done(self, i):
        self.load[i] -= 1

    def shutdown(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass


class _Operator:
    """One node of the operator graph: bounded inqueue -> budgeted
    in-flight remote tasks -> bounded outqueue (ref: execution/
    interfaces/physical_operator.py — an operator owns its task pool
    and exposes readiness to the scheduling loop)."""

    def __init__(self, name: str, budget: int, stats: StageStats,
                 depth: int):
        self.name = name
        self.budget = budget
        self.max_queue = 2 * budget   # inter-op queue bound
        self.stats = stats
        self.depth = depth
        self.inqueue: deque = deque()
        self.in_flight: deque = deque()   # (ref, extra) submission order
        self.outqueue: deque = deque()
        self.upstream_done = False

    # -- source feeding -------------------------------------------------
    def feed(self, item: Any) -> None:
        self.inqueue.append(item)
        self.stats.on_queue(len(self.inqueue))

    # -- scheduling interface -------------------------------------------
    def runnable(self) -> bool:
        return (bool(self.inqueue)
                and len(self.in_flight) < _effective_window(self.budget)
                and len(self.in_flight) + len(self.outqueue)
                < self.max_queue)

    def headroom(self) -> float:
        return 1.0 - len(self.in_flight) / max(1, self.budget)

    def submit_one(self) -> None:
        item = self.inqueue.popleft()
        ref, extra = self._launch(item)
        self.in_flight.append((ref, extra))
        self.stats.on_submit()
        self.stats.on_active(len(self.in_flight))

    def _launch(self, item):
        raise NotImplementedError

    def _on_done(self, extra) -> None:
        pass

    # -- completion harvest (in submission order) -----------------------
    def harvest(self) -> bool:
        progressed = False
        while self.in_flight:
            ref, extra = self.in_flight[0]
            done, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not done:
                break
            self.in_flight.popleft()
            self._on_done(extra)
            self.outqueue.append(ref)
            self.stats.on_output()
            progressed = True
        return progressed

    @property
    def finished(self) -> bool:
        return (self.upstream_done and not self.inqueue
                and not self.in_flight and not self.outqueue)

    def shutdown(self) -> None:
        pass


class _TaskMapOp(_Operator):
    def __init__(self, name, fused_fn, budget, stats, depth,
                 remote_fn=None, pack=None):
        super().__init__(name, budget, stats, depth)
        self._fn = fused_fn
        self._remote = remote_fn or ray_tpu.remote(_run_map)
        self._pack = pack or (lambda item, fn: (item, fn))

    def _launch(self, item):
        return self._remote.remote(*self._pack(item, self._fn)), None


class _ActorMapOp(_Operator):
    def __init__(self, name, stage: MapStage, stats, depth):
        self._stage = stage
        self._pool: Optional[_ActorPool] = None
        self._size = max(1, stage.num_actors)
        super().__init__(name, budget=2 * self._size, stats=stats,
                         depth=depth)

    def _launch(self, item):
        if self._pool is None:   # lazy: actors spawn on first block
            self._pool = _ActorPool(self._stage.actor_fn_maker,
                                    self._size)
        i, ref = self._pool.submit(item)
        return ref, i

    def _on_done(self, i) -> None:
        self._pool.done(i)

    def shutdown(self) -> None:
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None


def _split_actor_stages(stages: List[MapStage]):
    """Group consecutive task-fusable stages; actor stages break fusion."""
    groups: List[Any] = []
    cur: List[MapStage] = []
    for st in stages:
        if st.actor_fn_maker is not None:
            if cur:
                groups.append(cur)
                cur = []
            groups.append(st)
        else:
            cur.append(st)
    if cur:
        groups.append(cur)
    return groups


def _group_name(group) -> str:
    if isinstance(group, list):
        return "+".join(s.name for s in group) or "Map"
    return group.name


def _build_graph(map_stages, max_in_flight, stats: DatasetStats,
                 with_source: bool = False) -> List[_Operator]:
    """Linear operator graph for one barrier-free segment. With
    `with_source`, the head operator executes ReadTasks (fed lazily by
    _run_graph through the same bounded inqueue as every other op, so
    its queue stats reflect real backpressure, not the parallelism)."""
    ops: List[_Operator] = []
    groups = _split_actor_stages(map_stages)

    if with_source:
        head_fused = None
        head_name = "Read"
        if groups and isinstance(groups[0], list):
            head_fused = fuse_map_chain([s.block_fn for s in groups[0]])
            head_name = "Read+" + _group_name(groups[0])
            groups = groups[1:]
        ops.append(_TaskMapOp(head_name, head_fused,
                              budget=max_in_flight,
                              stats=stats.new_stage(head_name), depth=0,
                              remote_fn=ray_tpu.remote(_run_read),
                              pack=lambda task, fn: (task.fn, fn)))

    for g in groups:
        depth = len(ops)
        name = _group_name(g)
        if isinstance(g, list):
            fused = fuse_map_chain([s.block_fn for s in g])
            ops.append(_TaskMapOp(name, fused, budget=max_in_flight,
                                  stats=stats.new_stage(name),
                                  depth=depth))
        else:
            ops.append(_ActorMapOp(name, g, stats=stats.new_stage(name),
                                   depth=depth))
    return ops


def _run_graph(ops: List[_Operator],
               feed: Optional[Iterator[Any]] = None) -> Iterator[Any]:
    """The scheduling loop (ref: streaming_executor_state.py:494).

    Repeats: harvest completions -> propagate between bounded queues ->
    yield sink output -> advance the runnable operator with the most
    headroom. Blocks on the head in-flight refs only when no step can
    make progress. `feed` lazily supplies the first operator's input
    (refs from an upstream barrier)."""
    if not ops:
        if feed is not None:
            yield from feed
        return
    feed_done = feed is None
    try:
        while True:
            progressed = False
            # Pull upstream refs into the head inqueue (bounded).
            while (not feed_done
                   and len(ops[0].inqueue) < ops[0].max_queue):
                try:
                    ops[0].feed(next(feed))
                    progressed = True
                except StopIteration:
                    feed_done = True
                    ops[0].upstream_done = True
            for op in ops:
                progressed |= op.harvest()
            for up, down in zip(ops, ops[1:]):
                while (up.outqueue
                       and len(down.inqueue) < down.max_queue):
                    down.feed(up.outqueue.popleft())
                    progressed = True
                if up.finished and not down.upstream_done:
                    down.upstream_done = True
                    progressed = True
            while ops[-1].outqueue:
                yield ops[-1].outqueue.popleft()
                progressed = True
            runnable = [op for op in ops if op.runnable()]
            if runnable:
                # THE scheduling step: most free budget wins; ties go
                # downstream-most so the pipeline drains.
                best = max(runnable,
                           key=lambda op: (op.headroom(), op.depth))
                best.submit_one()
                progressed = True
            if progressed:
                continue
            if all(op.finished for op in ops) and feed_done:
                return
            heads = [op.in_flight[0][0] for op in ops if op.in_flight]
            if not heads:
                # Unreachable by construction: an op with queued input
                # and zero in-flight is always runnable (the sink
                # outqueue is drained above). Fail loudly rather than
                # busy-spin if a future runnable() change breaks that.
                raise RuntimeError(
                    "operator-graph deadlock: no progress, nothing in "
                    "flight, not finished — "
                    + ", ".join(
                        f"{op.name}(in={len(op.inqueue)} "
                        f"out={len(op.outqueue)} done={op.upstream_done})"
                        for op in ops))
            ray_tpu.wait(heads, num_returns=1, timeout=None)
    finally:
        for op in ops:
            op.shutdown()


def execute(read_tasks: List[ReadTask], stages: List[Any], *,
            max_in_flight: Optional[int] = None,
            stats: Optional[DatasetStats] = None) -> Iterator[Any]:
    """Yield block refs for the fully-applied plan, streaming."""
    if max_in_flight is None:
        max_in_flight = _default_window()
    if stats is None:
        stats = DatasetStats()
    # Split the stage list into segments separated by all-to-all barriers.
    segments: List[List[Any]] = [[]]
    for st in stages:
        if isinstance(st, AllToAllStage):
            segments.append(st)
            segments.append([])
        else:
            segments[-1].append(st)

    stream: Iterator[Any] = _run_graph(
        _build_graph(segments[0], max_in_flight, stats,
                     with_source=True),
        feed=iter(read_tasks))
    i = 1
    while i < len(segments):
        barrier: AllToAllStage = segments[i]
        bstat = stats.new_stage(barrier.name)
        bstat.on_submit()
        # ref_fn receives the (lazy) upstream ref iterator; most barriers
        # list() it, but streaming ones (Limit) can stop pulling early.
        refs = barrier.ref_fn(stream)
        bstat.on_output()
        ops = _build_graph(segments[i + 1], max_in_flight, stats)
        stream = _run_graph(ops, feed=iter(refs))
        i += 2
    yield from stream
