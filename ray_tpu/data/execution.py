"""Streaming executor: bounded in-flight tasks over the block stream.

The reference's streaming executor runs operators concurrently with
backpressure policies (ref: python/ray/data/_internal/execution/
streaming_executor.py:55, scheduling step :262; backpressure_policy/).
Equivalent mechanics here: read+fused-map work is submitted as remote
tasks with a sliding in-flight window (`max_in_flight`); completed block
refs stream to the consumer as soon as they finish (out-of-order), so
downstream iteration overlaps upstream compute.  Stateful UDF stages run
on a small actor pool with least-loaded dispatch.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.block import Block, concat
from ray_tpu.data.plan import AllToAllStage, MapStage, ReadTask, fuse_map_chain
from ray_tpu.data.stats import DatasetStats, StageStats

logger = logging.getLogger(__name__)

DEFAULT_MAX_IN_FLIGHT = 16


def _default_window() -> int:
    """Resource-aware base window (ref: backpressure_policy/
    concurrency_cap_backpressure_policy.py): enough in-flight tasks to
    cover the cluster's CPUs twice, bounded."""
    try:
        cpus = int(ray_tpu.cluster_resources().get("CPU", 4))
    except Exception:  # noqa: BLE001
        cpus = 4
    return max(4, min(2 * cpus, 64))


def _effective_window(base: int) -> int:
    """Shrink the window under object-store pressure (ref:
    backpressure_policy/streaming_output_backpressure_policy.py — the
    executor must not outrun consumers into an overflowing store)."""
    try:
        from ray_tpu.api import _global_worker

        store = _global_worker().store
        cap = getattr(store, "capacity", 0)
        if cap and store.used / cap > 0.85:
            return max(2, base // 4)
    except Exception:  # noqa: BLE001
        pass
    return base


def _run_read(read_fn, map_fn) -> Block:
    blocks = [read_fn()]
    if map_fn is not None:
        out: List[Block] = []
        for b in blocks:
            out.extend(map_fn(b))
        blocks = out
    return concat(blocks) if len(blocks) != 1 else blocks[0]


def _run_map(block: Block, map_fn) -> Block:
    out = list(map_fn(block))
    return concat(out) if len(out) != 1 else out[0]


class _ActorPool:
    """Small pool of UDF-holding actors with least-loaded dispatch
    (ref: execution/operators/actor_pool_map_operator.py)."""

    def __init__(self, fn_maker, size: int):
        @ray_tpu.remote
        class _MapActor:
            def __init__(self, maker):
                self._fn = maker()

            def apply(self, block):
                out = list(self._fn(block))
                return concat(out) if len(out) != 1 else out[0]

        self.actors = [_MapActor.remote(fn_maker) for _ in range(size)]
        self.load = [0] * size

    def submit(self, block_ref):
        i = min(range(len(self.actors)), key=lambda j: self.load[j])
        self.load[i] += 1
        ref = self.actors[i].apply.remote(block_ref)
        return i, ref

    def done(self, i):
        self.load[i] -= 1

    def shutdown(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass


def execute(read_tasks: List[ReadTask], stages: List[Any], *,
            max_in_flight: Optional[int] = None,
            stats: Optional[DatasetStats] = None) -> Iterator[Any]:
    """Yield block refs for the fully-applied plan, streaming."""
    if max_in_flight is None:
        max_in_flight = _default_window()
    if stats is None:
        stats = DatasetStats()
    # Split the stage list into segments separated by all-to-all barriers.
    segments: List[List[Any]] = [[]]
    for st in stages:
        if isinstance(st, AllToAllStage):
            segments.append(st)
            segments.append([])
        else:
            segments[-1].append(st)

    stream: Iterator[Any] = _stream_source(read_tasks, segments[0],
                                           max_in_flight, stats)
    i = 1
    while i < len(segments):
        barrier: AllToAllStage = segments[i]
        bstat = stats.new_stage(barrier.name)
        bstat.on_submit()
        # ref_fn receives the (lazy) upstream ref iterator; most barriers
        # list() it, but streaming ones (Limit) can stop pulling early.
        refs = barrier.ref_fn(stream)
        bstat.on_output()
        map_seg = segments[i + 1]
        stream = _stream_maps(iter(refs), map_seg, max_in_flight, stats)
        i += 2
    yield from stream


def _split_actor_stages(stages: List[MapStage]):
    """Group consecutive task-fusable stages; actor stages break fusion."""
    groups: List[Any] = []
    cur: List[MapStage] = []
    for st in stages:
        if st.actor_fn_maker is not None:
            if cur:
                groups.append(cur)
                cur = []
            groups.append(st)
        else:
            cur.append(st)
    if cur:
        groups.append(cur)
    return groups


def _group_name(group) -> str:
    if isinstance(group, list):
        return "+".join(s.name for s in group) or "Map"
    return group.name


def _stream_source(read_tasks, map_stages, max_in_flight,
                   stats: DatasetStats) -> Iterator[Any]:
    groups = _split_actor_stages(map_stages)
    head_fused = None
    head_name = "Read"
    if groups and isinstance(groups[0], list):
        head_fused = fuse_map_chain([s.block_fn for s in groups[0]])
        head_name = "Read+" + _group_name(groups[0])
        groups = groups[1:]

    run_read = ray_tpu.remote(_run_read)
    stream = _windowed(
        ((run_read, (t.fn, head_fused)) for t in read_tasks), max_in_flight,
        stats.new_stage(head_name))
    for g in groups:
        stream = _apply_group(stream, g, max_in_flight, stats)
    return stream


def _stream_maps(refs: Iterator[Any], map_stages, max_in_flight,
                 stats: DatasetStats):
    groups = _split_actor_stages(map_stages)
    stream = refs
    for g in groups:
        stream = _apply_group(stream, g, max_in_flight, stats)
    return stream


def _apply_group(stream: Iterator[Any], group, max_in_flight,
                 stats: DatasetStats):
    stage_stats = stats.new_stage(_group_name(group))
    if isinstance(group, list):
        fused = fuse_map_chain([s.block_fn for s in group])
        run_map = ray_tpu.remote(_run_map)
        return _windowed(((run_map, (ref, fused)) for ref in stream),
                         max_in_flight, stage_stats)
    return _actor_stream(stream, group, max_in_flight, stage_stats)


def _windowed(submissions, max_in_flight,
              stage_stats: Optional[StageStats] = None) -> Iterator[Any]:
    """Submit (remote_fn, args) lazily, keep <= max_in_flight running,
    yield refs in submission order (blocks stay ordered like the
    reference's default; the window still overlaps execution). The
    window shrinks under object-store pressure (backpressure policy)."""
    in_flight: List[Any] = []
    submissions = iter(submissions)
    exhausted = False
    while True:
        window = _effective_window(max_in_flight)
        while not exhausted and len(in_flight) < window:
            try:
                fn, args = next(submissions)
            except StopIteration:
                exhausted = True
                break
            in_flight.append(fn.remote(*args))
            if stage_stats is not None:
                stage_stats.on_submit()
        if not in_flight:
            return
        head = in_flight.pop(0)
        ray_tpu.wait([head], num_returns=1, timeout=None)
        if stage_stats is not None:
            stage_stats.on_output()
        yield head


def _actor_stream(stream: Iterator[Any], stage: MapStage, max_in_flight,
                  stage_stats: Optional[StageStats] = None):
    pool = _ActorPool(stage.actor_fn_maker, max(1, stage.num_actors))
    try:
        pending: List[Any] = []  # (ref, actor_idx) in submission order
        stream = iter(stream)
        exhausted = False
        cap = max(len(pool.actors) * 2, 2)
        while True:
            while not exhausted and len(pending) < cap:
                try:
                    block_ref = next(stream)
                except StopIteration:
                    exhausted = True
                    break
                i, ref = pool.submit(block_ref)
                if stage_stats is not None:
                    stage_stats.on_submit()
                pending.append((ref, i))
            if not pending:
                return
            ref, i = pending.pop(0)
            ray_tpu.wait([ref], num_returns=1, timeout=None)
            pool.done(i)
            if stage_stats is not None:
                stage_stats.on_output()
            yield ref
    finally:
        pool.shutdown()
