"""Self-contained TFRecord + tf.train.Example codec.

Analogue of the reference's tfrecords datasource (ref: python/ray/data/
datasource/tfrecords_datasource.py — which imports tensorflow/crc32c).
This image is zero-egress and has no tensorflow, so the wire formats are
implemented directly:

  TFRecord framing: u64le length | u32le masked-crc32c(length) |
                    payload | u32le masked-crc32c(payload)
  tf.train.Example: a protobuf with
      Example{ features:1 } / Features{ map<string,Feature> feature:1 }
      Feature{ bytes_list:1 | float_list:2 | int64_list:3 }
      BytesList{ repeated bytes value:1 }
      FloatList{ repeated float value:1 (packed) }
      Int64List{ repeated int64 value:1 (packed) }

Only the wire-format subset Example needs is implemented (varints,
length-delimited fields, fixed32 floats).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

# ---------------------------------------------------------------------------
# crc32c (Castagnoli) — table-driven; the masking is the TFRecord scheme
# ---------------------------------------------------------------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecord framing
# ---------------------------------------------------------------------------

def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            head = f.read(12)
            if len(head) < 12:
                return
            (length,), (lcrc,) = (struct.unpack("<Q", head[:8]),
                                  struct.unpack("<I", head[8:]))
            if _masked_crc(head[:8]) != lcrc:
                raise ValueError(f"corrupt tfrecord length crc in {path}")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if _masked_crc(payload) != pcrc:
                raise ValueError(f"corrupt tfrecord data crc in {path}")
            yield payload


def write_records(path: str, payloads: Iterator[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for p in payloads:
            head = struct.pack("<Q", len(p))
            f.write(head)
            f.write(struct.pack("<I", _masked_crc(head)))
            f.write(p)
            f.write(struct.pack("<I", _masked_crc(p)))
            n += 1
    return n


# ---------------------------------------------------------------------------
# minimal protobuf wire helpers
# ---------------------------------------------------------------------------

def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field (wire type 2)."""
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _iter_fields(buf: bytes) -> Iterator[tuple]:
    """Yield (field_number, wire_type, value, value_end)."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# ---------------------------------------------------------------------------
# tf.train.Example encode/decode
# ---------------------------------------------------------------------------

def encode_example(row: Dict[str, Any]) -> bytes:
    """Dict -> serialized Example. bytes/str -> BytesList, float ->
    FloatList, int/bool -> Int64List; lists/arrays of those likewise."""
    import numpy as np

    entries = b""
    for key, value in row.items():
        if isinstance(value, np.ndarray):
            value = value.tolist()
        if not isinstance(value, (list, tuple)):
            value = [value]
        if not value:
            feature = _ld(3, b"")
        elif isinstance(value[0], (bytes, str)):
            items = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else v)
                for v in value)
            feature = _ld(1, items)
        elif isinstance(value[0], (bool, int, np.integer)):
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                              for v in value)
            feature = _ld(3, _ld(1, packed))
        elif isinstance(value[0], float) or hasattr(value[0], "__float__"):
            packed = b"".join(struct.pack("<f", float(v)) for v in value)
            feature = _ld(2, _ld(1, packed))
        else:
            raise TypeError(f"unsupported feature type for {key!r}: "
                            f"{type(value[0]).__name__}")
        entry = _ld(1, key.encode()) + _ld(2, feature)
        entries += _ld(1, entry)
    features = entries
    return _ld(1, features)


def decode_example(payload: bytes) -> Dict[str, Any]:
    row: Dict[str, Any] = {}
    for field, _, features in _iter_fields(payload):
        if field != 1:
            continue
        for f2, _, entry in _iter_fields(features):
            if f2 != 1:
                continue
            key = None
            feature = b""
            for f3, _, v in _iter_fields(entry):
                if f3 == 1:
                    key = v.decode()
                elif f3 == 2:
                    feature = v
            if key is None:
                continue
            row[key] = _decode_feature(feature)
    return row


def _signed64(val: int) -> int:
    return val - (1 << 64) if val >= 1 << 63 else val


def _decode_feature(feature: bytes) -> Any:
    """Both packed and unpacked repeated encodings are accepted (packed
    is merely the default on the wire; conformant parsers must read
    either), accumulating every occurrence."""
    for field, _, body in _iter_fields(feature):
        if field == 1:      # BytesList
            values = [v for f, _, v in _iter_fields(body) if f == 1]
            return values[0] if len(values) == 1 else values
        if field == 2:      # FloatList
            floats: list = []
            for f, wire, v in _iter_fields(body):
                if f != 1:
                    continue
                if wire == 2:       # packed run
                    floats.extend(struct.unpack_from("<f", v, i)[0]
                                  for i in range(0, len(v), 4))
                elif wire == 5:     # unpacked element
                    floats.append(struct.unpack("<f", v)[0])
            return floats[0] if len(floats) == 1 else floats
        if field == 3:      # Int64List
            ints: list = []
            for f, wire, v in _iter_fields(body):
                if f != 1:
                    continue
                if wire == 2:       # packed run
                    pos = 0
                    while pos < len(v):
                        val, pos = _read_varint(v, pos)
                        ints.append(_signed64(val))
                elif wire == 0:     # unpacked element
                    ints.append(_signed64(v))
            return ints[0] if len(ints) == 1 else ints
    return None
