"""Logical plan: read tasks + stages, with map-stage fusion.

Reference shape: lazy logical→physical planning + streaming execution
(ref: python/ray/data/_internal/logical/, planner/, execution/
streaming_executor.py:55).  Simplified two-kind algebra: `MapStage`
(block→blocks, fused into its upstream producer task) and `AllToAllStage`
(needs the full upstream ref list: shuffle/sort/repartition/groupby).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, List, Optional

from ray_tpu.data.block import Block


@dataclasses.dataclass
class ReadTask:
    """A deferred producer of one block (readers pre-split work into these)."""
    fn: Callable[[], Block]
    name: str = "read"


@dataclasses.dataclass
class MapStage:
    """block -> iterable[Block]; pure function of one block, fusable."""
    name: str
    block_fn: Callable[[Block], Iterable[Block]]
    # Stateful UDF support (ActorPool compute): when set, block_fn is
    # produced per-actor by calling make_fn(cls_args already bound).
    actor_fn_maker: Optional[Callable[[], Callable[[Block], Iterable[Block]]]] = None
    num_actors: int = 0


@dataclasses.dataclass
class AllToAllStage:
    """list[ref] -> list[ref]; materializes its input frontier."""
    name: str
    ref_fn: Callable[[List[Any]], List[Any]]  # refs in, refs out


Stage = Any  # MapStage | AllToAllStage


def fuse_map_chain(fns: List[Callable[[Block], Iterable[Block]]]
                   ) -> Callable[[Block], Iterable[Block]]:
    def fused(block: Block) -> Iterable[Block]:
        blocks = [block]
        for fn in fns:
            nxt: List[Block] = []
            for b in blocks:
                nxt.extend(fn(b))
            blocks = nxt
        return blocks

    return fused
