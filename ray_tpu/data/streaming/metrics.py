"""Data-plane observability: per-operator gauges for the streaming
executor, federated over the existing report-gauges → syncer → GCS
path (the same ``report_metrics`` RPC the serve plane pushes through),
so they show up in ``ray-tpu metrics --federated`` next to transfer
and serve metrics.

Gauges are process-local (registered once in whatever process runs the
executor — usually the driver) and pushed best-effort after each
execution plus whenever a prefetcher closes; a missing daemon (local
mode, unit tests) degrades to registry-only.
"""
from __future__ import annotations

from typing import Optional

from ray_tpu.util.metrics import Counter, Gauge

_M: Optional[dict] = None


def _metrics() -> dict:
    global _M
    if _M is None:
        _M = {
            "blocks_inflight": Gauge(
                "data_op_blocks_in_flight",
                "Blocks produced by the operator awaiting consumption",
                ("dataset", "operator")),
            "bytes_inflight": Gauge(
                "data_op_bytes_in_flight",
                "Produced-but-unconsumed bytes charged to the operator",
                ("dataset", "operator")),
            "stall_seconds": Gauge(
                "data_op_stall_seconds",
                "Seconds the operator sat byte-backpressured",
                ("dataset", "operator")),
            "bytes_out": Counter(
                "data_op_bytes_out",
                "Total bytes produced by the operator",
                ("dataset", "operator")),
            "spilled_tasks": Counter(
                "data_op_spilled_tasks",
                "Over-budget submissions taken via the spill fallback",
                ("dataset", "operator")),
            "shuffle_gbps": Gauge(
                "data_shuffle_gbps",
                "Aggregate GB/s of the most recent all-to-all shuffle",
                ("dataset",)),
            "prefetch_hits": Counter(
                "data_prefetch_hits",
                "Device batches already resident when the consumer asked",
                ("dataset",)),
            "prefetch_misses": Counter(
                "data_prefetch_misses",
                "Device-batch requests that had to wait on the pipeline",
                ("dataset",)),
        }
    return _M


def _push(origin: str = "data") -> None:
    from ray_tpu.serve.observability import push_registry

    push_registry(origin)


def on_execution(dataset: str, stats) -> None:
    """Fold one finished (or abandoned) execution's DatasetStats into
    the gauges and push toward the federation path."""
    try:
        m = _metrics()
        for st in stats.stages:
            tags = {"dataset": dataset, "operator": st.name}
            m["bytes_inflight"].set(float(st.peak_inflight_bytes), tags)
            m["blocks_inflight"].set(float(st.peak_queue), tags)
            m["stall_seconds"].set(st.stall_s, tags)
            if st.bytes_out:
                m["bytes_out"].inc(float(st.bytes_out), tags)
            if st.spilled_tasks:
                m["spilled_tasks"].inc(float(st.spilled_tasks), tags)
        _push()
    except Exception:  # noqa: BLE001 — telemetry must never break the plane
        pass


def on_shuffle(dataset: str, nbytes: int, seconds: float) -> None:
    try:
        if seconds > 0:
            _metrics()["shuffle_gbps"].set(nbytes / seconds / 1e9,
                                           {"dataset": dataset})
        _push()
    except Exception:  # noqa: BLE001
        pass


def on_prefetch(dataset: str, hits: int, misses: int) -> None:
    """One prefetcher lifetime's counts (recorded once, at close)."""
    try:
        m = _metrics()
        tags = {"dataset": dataset}
        if hits:
            m["prefetch_hits"].inc(float(hits), tags)
        if misses:
            m["prefetch_misses"].inc(float(misses), tags)
        _push()
    except Exception:  # noqa: BLE001
        pass
