"""Pipeline-resident device prefetch for ``iter_jax_batches``.

The legacy feed issued ``jax.device_put`` inline on the consumer
thread: batch formation, host→HBM transfer, and compute all serialize.
Here a background thread owns the whole host side — it pulls numpy
batches from the (already streaming) block iterator, applies the
dtype/sharding transform, and parks up to ``depth`` device-resident
batches in a bounded queue.  With ``depth=2`` (the default knob) the
transfer of batch k+1 overlaps compute on batch k — classic double
buffering (see the tf.data/`jax` host-offload idiom the paper's data
layer describes).

Hit/miss accounting feeds the data-plane gauges: a *hit* means the
consumer found a batch already resident when it asked (the pipeline is
ahead of the accelerator); a run of misses means ingestion is the
bottleneck and shows up directly in ``bench_data.py``'s train-busy
probe.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional

_SENTINEL = object()


class DevicePrefetcher:
    """Bounded background producer of device-resident batches."""

    def __init__(self, batch_iter: Iterator[Any],
                 to_device: Callable[[Any], Any], *,
                 depth: int = 2, name: str = "train"):
        self._src = batch_iter
        self._to_device = to_device
        self._depth = max(1, depth)
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self.hits = 0
        self.misses = 0
        self._recorded = False
        self._name = name
        self._thread = threading.Thread(
            target=self._run, name=f"data-prefetch-{name}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            for batch in self._src:
                dev = self._to_device(batch)
                while not self._stop.is_set():
                    try:
                        self._q.put(dev, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — surface at consumer
            self._err = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        try:
            item = self._q.get_nowait()
            self.hits += 1
        except queue.Empty:
            self.misses += 1
            t0 = time.perf_counter()
            item = self._q.get()
            # A blocked get IS the input pipeline stalling the step
            # loop: charge it to the active train session's data_wait
            # phase (no-op outside a training step loop) so
            # StreamingIngest-fed loops get attribution for free.
            try:
                from ray_tpu.train import observability as _tobs

                _tobs.on_data_wait(time.perf_counter() - t0)
            except Exception:  # noqa: BLE001 — accounting must never break
                pass
        if item is _SENTINEL:
            self._record()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer early (consumer abandoned the epoch)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._record()

    def _record(self) -> None:
        if self._recorded:
            return
        self._recorded = True
        try:
            from ray_tpu.data.streaming import metrics as dm

            dm.on_prefetch(self._name, self.hits, self.misses)
        except Exception:  # noqa: BLE001 — accounting must never break
            pass


def device_prefetching(batch_iter: Iterator[Any], to_device, *,
                       depth: int, name: str = "train") -> Iterator[Any]:
    """Generator wrapper that guarantees producer shutdown when the
    consumer stops early (break out of a partial epoch)."""
    pf = DevicePrefetcher(batch_iter, to_device, depth=depth, name=name)
    try:
        yield from pf
    finally:
        pf.close()
