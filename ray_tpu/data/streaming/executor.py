"""Byte-budgeted streaming operator graph — the default Dataset path.

Extends the operator-graph executor (data/execution.py, kept as the
``RAY_TPU_DATA_STREAM_ENABLED=0`` fallback) with the reference's
byte-based backpressure model (ref: python/ray/data/_internal/execution/
backpressure_policy/streaming_output_backpressure_policy.py): operator
tasks return ``(block, meta)`` with ``num_returns=2`` so the tiny meta
object (rows/bytes) is fetched at harvest without materializing the
block, and every operator is charged for the bytes it has produced that
no downstream consumer has picked up yet.

Backpressure composes four ways here:

- task budget + bounded queues, inherited from the legacy executor
  (shrunk under object-store pressure via ``_effective_window``);
- a per-operator in-flight byte cap (``data_stream_op_inflight_bytes``)
  — an operator over its cap stops submitting, and the seconds it sits
  byte-blocked are accounted per stage in ``Dataset.stats()``;
- a global bytes window (``data_stream_window_bytes``) across the whole
  graph;
- the consumer: the executor is a generator, so when the caller stops
  pulling, scheduling pauses — and yielding a block to the caller is
  what releases its producer's budget.

Liveness: when the graph is byte-wedged with nothing in flight (a
single block larger than the window), the downstream-most blocked
operator is allowed one over-budget submission — the *spill fallback*,
accounted as ``spilled_tasks`` — as long as the local object store is
below ``data_stream_spill_threshold`` (beyond that the store's own
disk spilling is already straining). With no spill headroom the
executor raises :class:`~ray_tpu.exceptions.BackpressureTimeout` after
``data_stream_stall_timeout_s`` of zero forward progress instead of
deadlocking silently.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.config import get_config
from ray_tpu.data.block import concat
from ray_tpu.data.execution import (
    _default_window,
    _effective_window,
    _group_name,
    _Operator,
    _split_actor_stages,
)
from ray_tpu.data.plan import AllToAllStage, MapStage, ReadTask, fuse_map_chain
from ray_tpu.data.stats import DatasetStats
from ray_tpu.exceptions import BackpressureTimeout

logger = logging.getLogger(__name__)


def streaming_enabled() -> bool:
    return get_config().data_stream_enabled


def _store_fraction() -> float:
    """Local object-store used/capacity; 0.0 when unknowable (spill
    fallback stays available rather than wedging a storeless test)."""
    try:
        from ray_tpu.api import _global_worker

        store = _global_worker().store
        cap = getattr(store, "capacity", 0)
        if cap:
            return store.used / cap
    except Exception:  # noqa: BLE001
        pass
    return 0.0


def _meta(blk) -> dict:
    return {"rows": blk.num_rows, "bytes": blk.nbytes}


def _run_read_meta(read_fn, map_fn):
    blocks = [read_fn()]
    if map_fn is not None:
        out: List[Any] = []
        for b in blocks:
            out.extend(map_fn(b))
        blocks = out
    blk = concat(blocks) if len(blocks) != 1 else blocks[0]
    return blk, _meta(blk)


def _run_map_meta(block, map_fn):
    out = list(map_fn(block))
    blk = concat(out) if len(out) != 1 else out[0]
    return blk, _meta(blk)


class _ByteBudget:
    """Shared byte ledger for one graph: global window + per-op cap."""

    def __init__(self, window_bytes: int, op_cap: int):
        self.window = max(1, window_bytes)
        self.op_cap = max(1, op_cap)
        self.total = 0


class _StreamItem:
    """A block ref flowing between operators, charged to its producer
    until a downstream submission (or the sink consumer) picks it up."""

    __slots__ = ("ref", "nbytes", "rows", "producer")

    def __init__(self, ref, nbytes: int, rows: int, producer):
        self.ref = ref
        self.nbytes = nbytes
        self.rows = rows
        self.producer = producer

    def consume(self):
        """Release the producer's byte charge; returns the bare ref."""
        if self.producer is not None:
            self.producer.release(self.nbytes)
            self.producer = None
        return self.ref


def _consume(item):
    return item.consume() if isinstance(item, _StreamItem) else item


class _StreamOp(_Operator):
    """Operator with produced-but-unconsumed byte accounting."""

    def __init__(self, name, budget, stats, depth, bytebudget: _ByteBudget):
        super().__init__(name, budget, stats, depth)
        self.bytebudget = bytebudget
        self.unconsumed = 0

    # -- byte ledger ----------------------------------------------------
    def charge(self, nbytes: int) -> None:
        self.unconsumed += nbytes
        self.bytebudget.total += nbytes
        self.stats.on_inflight_bytes(self.unconsumed)

    def release(self, nbytes: int) -> None:
        self.unconsumed -= nbytes
        self.bytebudget.total -= nbytes

    # -- scheduling interface -------------------------------------------
    def byte_blocked(self) -> bool:
        return (self.unconsumed >= self.bytebudget.op_cap
                or self.bytebudget.total >= self.bytebudget.window)

    def task_runnable(self) -> bool:
        return super().runnable()

    def runnable(self) -> bool:
        return self.task_runnable() and not self.byte_blocked()

    def stalled(self) -> bool:
        """Has work and task headroom but is held back purely by bytes —
        the condition whose duration lands in ``stats.stall_s``."""
        return self.task_runnable() and self.byte_blocked()

    # -- completion harvest ---------------------------------------------
    def harvest(self) -> bool:
        progressed = False
        while self.in_flight:
            (block_ref, meta_ref), extra = self.in_flight[0]
            done, _ = ray_tpu.wait([block_ref], num_returns=1, timeout=0)
            if not done:
                break
            self.in_flight.popleft()
            self._on_done(extra)
            try:
                m = ray_tpu.get(meta_ref)
                rows, nbytes = int(m["rows"]), int(m["bytes"])
            except Exception:  # noqa: BLE001 — task failed: let the
                rows, nbytes = 0, 0   # error surface at the consumer's get
            self.charge(nbytes)
            self.outqueue.append(_StreamItem(block_ref, nbytes, rows, self))
            self.stats.on_output(rows, nbytes)
            progressed = True
        return progressed


class _StreamTaskMapOp(_StreamOp):
    def __init__(self, name, fused_fn, budget, stats, depth, bytebudget,
                 remote_fn=None, pack=None):
        super().__init__(name, budget, stats, depth, bytebudget)
        self._fn = fused_fn
        self._remote = (remote_fn
                        or ray_tpu.remote(_run_map_meta)
                        ).options(num_returns=2)
        self._pack = pack or (lambda item, fn: (_consume(item), fn))

    def _launch(self, item):
        refs = self._remote.remote(*self._pack(item, self._fn))
        return tuple(refs), None


class _StreamActorPool:
    """Least-loaded actor pool whose UDF actors also report block meta
    (mirror of execution._ActorPool with ``num_returns=2`` methods)."""

    def __init__(self, fn_maker, size: int):
        @ray_tpu.remote
        class _MapActor:
            def __init__(self, maker):
                self._fn = maker()

            def apply(self, block):
                out = list(self._fn(block))
                blk = concat(out) if len(out) != 1 else out[0]
                return blk, _meta(blk)

        self.actors = [_MapActor.remote(fn_maker) for _ in range(size)]
        self._apply = [a.apply.options(num_returns=2) for a in self.actors]
        self.load = [0] * size

    def submit(self, block_ref):
        i = min(range(len(self.actors)), key=lambda j: self.load[j])
        self.load[i] += 1
        refs = self._apply[i].remote(block_ref)
        return i, tuple(refs)

    def done(self, i):
        self.load[i] -= 1

    def shutdown(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass


class _StreamActorMapOp(_StreamOp):
    def __init__(self, name, stage: MapStage, stats, depth, bytebudget):
        self._stage = stage
        self._pool: Optional[_StreamActorPool] = None
        self._size = max(1, stage.num_actors)
        super().__init__(name, budget=2 * self._size, stats=stats,
                         depth=depth, bytebudget=bytebudget)

    def _launch(self, item):
        if self._pool is None:   # lazy: actors spawn on first block
            self._pool = _StreamActorPool(self._stage.actor_fn_maker,
                                          self._size)
        i, refs = self._pool.submit(_consume(item))
        return refs, i

    def _on_done(self, i) -> None:
        self._pool.done(i)

    def shutdown(self) -> None:
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None


def _build_stream_graph(map_stages, max_in_flight, stats: DatasetStats,
                        bytebudget: _ByteBudget,
                        with_source: bool = False) -> List[_StreamOp]:
    """Linear streaming-operator graph for one barrier-free segment
    (same segmentation/fusion rules as execution._build_graph)."""
    ops: List[_StreamOp] = []
    groups = _split_actor_stages(map_stages)

    if with_source:
        head_fused = None
        head_name = "Read"
        if groups and isinstance(groups[0], list):
            head_fused = fuse_map_chain([s.block_fn for s in groups[0]])
            head_name = "Read+" + _group_name(groups[0])
            groups = groups[1:]
        ops.append(_StreamTaskMapOp(
            head_name, head_fused, budget=max_in_flight,
            stats=stats.new_stage(head_name), depth=0,
            bytebudget=bytebudget,
            remote_fn=ray_tpu.remote(_run_read_meta),
            pack=lambda task, fn: (task.fn, fn)))

    for g in groups:
        depth = len(ops)
        name = _group_name(g)
        if isinstance(g, list):
            fused = fuse_map_chain([s.block_fn for s in g])
            ops.append(_StreamTaskMapOp(name, fused, budget=max_in_flight,
                                        stats=stats.new_stage(name),
                                        depth=depth, bytebudget=bytebudget))
        else:
            ops.append(_StreamActorMapOp(name, g,
                                         stats=stats.new_stage(name),
                                         depth=depth, bytebudget=bytebudget))
    return ops


def _run_stream_graph(ops: List[_StreamOp],
                      feed: Optional[Iterator[Any]] = None) -> Iterator[Any]:
    """Scheduling loop: harvest -> propagate -> yield sink -> submit the
    runnable op with the most headroom (ties downstream-most), exactly
    as execution._run_graph — plus stall accounting on byte-blocked
    operators, the spill fallback, and the stall deadline."""
    if not ops:
        if feed is not None:
            yield from (_consume(x) for x in feed)
        return
    cfg = get_config()
    stall_deadline = max(0.01, cfg.data_stream_stall_timeout_s)
    feed_done = feed is None
    last_progress = time.monotonic()
    prev_stalled: List[_StreamOp] = []
    prev_t = last_progress
    try:
        while True:
            now = time.monotonic()
            # Accrue the time since the last pass to every operator that
            # spent it byte-blocked (busy passes contribute ~0; blocking
            # waits below are where stall seconds actually come from).
            for op in prev_stalled:
                op.stats.on_stall(now - prev_t)
            prev_t = now

            progressed = False
            while (not feed_done
                   and len(ops[0].inqueue) < ops[0].max_queue):
                try:
                    ops[0].feed(next(feed))
                    progressed = True
                except StopIteration:
                    feed_done = True
                    ops[0].upstream_done = True
            for op in ops:
                progressed |= op.harvest()
            for up, down in zip(ops, ops[1:]):
                while (up.outqueue
                       and len(down.inqueue) < down.max_queue):
                    down.feed(up.outqueue.popleft())
                    progressed = True
                if up.finished and not down.upstream_done:
                    down.upstream_done = True
                    progressed = True
            while ops[-1].outqueue:
                # Yielding transfers the byte charge to the consumer.
                yield ops[-1].outqueue.popleft().consume()
                progressed = True
            runnable = [op for op in ops if op.runnable()]
            if runnable:
                best = max(runnable,
                           key=lambda op: (op.headroom(), op.depth))
                best.submit_one()
                progressed = True
            prev_stalled = [op for op in ops if op.stalled()]
            if progressed:
                last_progress = time.monotonic()
                continue
            if all(op.finished for op in ops) and feed_done:
                return
            waited = time.monotonic() - last_progress
            heads = [op.in_flight[0][0][0] for op in ops if op.in_flight]
            if heads:
                # Bounded wait so stall seconds keep accruing and the
                # deadline below stays live even if a task never lands.
                ray_tpu.wait(heads, num_returns=1,
                             timeout=min(0.5, stall_deadline))
                if not prev_stalled:
                    # Plain slow tasks, not backpressure: don't let the
                    # stall deadline fire on them.
                    last_progress = time.monotonic()
                continue
            if prev_stalled:
                if waited > stall_deadline:
                    worst = max(prev_stalled, key=lambda op: op.stats.stall_s)
                    raise BackpressureTimeout(
                        operator=worst.name, waited_s=worst.stats.stall_s,
                        inflight_bytes=worst.bytebudget.total)
                if _store_fraction() < cfg.data_stream_spill_threshold:
                    # Spill fallback: one over-budget submission so the
                    # graph keeps moving; the store absorbs the overrun
                    # (spilling to disk past its own threshold).
                    best = max(prev_stalled, key=lambda op: op.depth)
                    best.submit_one()
                    best.stats.spilled_tasks += 1
                    last_progress = time.monotonic()
                    continue
                time.sleep(min(0.05, stall_deadline / 4))
                continue
            raise RuntimeError(
                "operator-graph deadlock: no progress, nothing in "
                "flight, not finished — "
                + ", ".join(
                    f"{op.name}(in={len(op.inqueue)} "
                    f"out={len(op.outqueue)} done={op.upstream_done})"
                    for op in ops))
    finally:
        for op in ops:
            op.shutdown()


def streaming_execute(read_tasks: List[ReadTask], stages: List[Any], *,
                      max_in_flight: Optional[int] = None,
                      stats: Optional[DatasetStats] = None) -> Iterator[Any]:
    """Yield block refs for the fully-applied plan through the
    byte-budgeted streaming graph (drop-in for execution.execute)."""
    cfg = get_config()
    if max_in_flight is None:
        max_in_flight = _default_window()
    if stats is None:
        stats = DatasetStats()
    bytebudget = _ByteBudget(cfg.data_stream_window_bytes,
                             cfg.data_stream_op_inflight_bytes)

    segments: List[List[Any]] = [[]]
    for st in stages:
        if isinstance(st, AllToAllStage):
            segments.append(st)
            segments.append([])
        else:
            segments[-1].append(st)

    stream: Iterator[Any] = _run_stream_graph(
        _build_stream_graph(segments[0], max_in_flight, stats, bytebudget,
                            with_source=True),
        feed=iter(read_tasks))
    i = 1
    while i < len(segments):
        barrier: AllToAllStage = segments[i]
        bstat = stats.new_stage(barrier.name)
        bstat.on_submit()
        refs = barrier.ref_fn(stream)
        bstat.on_output()
        ops = _build_stream_graph(segments[i + 1], max_in_flight, stats,
                                  bytebudget)
        stream = _run_stream_graph(ops, feed=iter(refs))
        i += 2
    yield from (_consume(x) for x in stream)
