"""All-to-all shuffle over the zero-copy transfer plane.

The legacy shuffle (dataset.random_shuffle) moves every mapper→reducer
partition as its own pickled object through point-to-point gets — N²
small transfers per round, each paying the pickle codec and its own RPC
slow-start. The streaming shuffle instead has every mapper emit ONE
sealed *bundle* — all of its reducer partitions packed back-to-back
behind a fixed-size offset header — and moves bundles over the
transfer plane:

- **relay-tree pre-staging** (multi-node): each bundle is broadcast to
  every node over the daemon relay tree (`plan_broadcast_tree` /
  `broadcast_object` — raw frames, pipelined chunks, log-N depth), so
  reducer tasks find their input node-local no matter where they
  schedule;
- **range serve**: because the bundle layout is offset-addressed, a
  reducer can also pull JUST its partition's byte range of a remote
  bundle (`transfer.fetch_object_range` → daemon `get_object_chunk`,
  which serves sealed and still-arriving objects alike) — same total
  bytes as point-to-point, but raw-framed and windowed.

Partitions are Arrow IPC streams, so a reducer deserializes its slice
without touching the rest of the bundle.
"""
from __future__ import annotations

import logging
import struct
import time
from typing import Any, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import concat

logger = logging.getLogger(__name__)

_MAGIC = b"RTSB"
_HEAD = struct.Struct("<4sI")      # magic, n_parts
_SLOT = struct.Struct("<QQ")       # offset, length


def header_size(n_parts: int) -> int:
    return _HEAD.size + n_parts * _SLOT.size


def table_to_ipc(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(buf) -> pa.Table:
    return pa.ipc.open_stream(pa.BufferReader(pa.py_buffer(buf))).read_all()


def pack_bundle(parts: List[bytes]) -> bytes:
    """Offset-addressed bundle: header with (offset, length) per part,
    payloads concatenated — the layout range readers slice into."""
    n = len(parts)
    off = header_size(n)
    slots = []
    for p in parts:
        slots.append((off, len(p)))
        off += len(p)
    out = bytearray(off)
    _HEAD.pack_into(out, 0, _MAGIC, n)
    pos = _HEAD.size
    for s in slots:
        _SLOT.pack_into(out, pos, *s)
        pos += _SLOT.size
    w = header_size(n)
    for p in parts:
        out[w:w + len(p)] = p
        w += len(p)
    return bytes(out)


def parse_header(buf) -> List[Tuple[int, int]]:
    magic, n = _HEAD.unpack_from(buf, 0)
    if magic != _MAGIC:
        raise ValueError("not a shuffle bundle (bad magic)")
    return [_SLOT.unpack_from(buf, _HEAD.size + i * _SLOT.size)
            for i in range(n)]


def unpack_part(buf, j: int) -> memoryview:
    off, ln = parse_header(buf)[j]
    return memoryview(buf)[off:off + ln]


def part_table(bundle, j: int) -> pa.Table:
    return ipc_to_table(unpack_part(bundle, j))


# -- remote shuffle stages ------------------------------------------------

def _scatter_bundle(block, n: int, seed: int):
    """Mapper: permute rows, split into n partitions, pack ONE bundle.
    Second return is the bundle size — a tiny inline object, so the
    driver can account shuffle bytes without fetching a bundle."""
    rng = np.random.default_rng(seed)
    idx = rng.permutation(block.num_rows)
    parts = np.array_split(idx, n)
    bundle = pack_bundle([
        table_to_ipc(block.take(pa.array(p))) for p in parts])
    return bundle, len(bundle)


def _combine_part(seed: int, j: int, *bundles) -> pa.Table:
    """Reducer: partition j of every bundle, concatenated + permuted."""
    t = concat([part_table(b, j) for b in bundles])
    rng = np.random.default_rng(seed)
    return t.take(pa.array(rng.permutation(t.num_rows)))


def _prestage(bundle_refs: List[Any], fanout: int) -> int:
    """Broadcast each sealed bundle to every live node over the relay
    tree so reducers read node-locally. Best-effort: a failed prestage
    only costs the reducer a remote pull. Returns nodes staged."""
    try:
        from ray_tpu.api import _global_worker

        worker = _global_worker()
        node_ids = [n["node_id"] for n in worker.nodes()
                    if n.get("alive", True)]
        if len(node_ids) <= 1:
            return 0
        staged = 0
        for ref in bundle_refs:
            res = worker.broadcast_object(ref, node_ids)
            staged += int(res.get("nodes", 0)) if res.get("ok") else 0
        return staged
    except Exception:  # noqa: BLE001 — prestage is an optimization
        logger.debug("shuffle prestage skipped", exc_info=True)
        return 0


def streaming_shuffle_refs(refs: List[Any],
                           seed: Optional[int] = None,
                           dataset: str = "ds") -> List[Any]:
    """ref_fn body for the streaming RandomShuffle barrier: bundles out
    of mappers, relay-tree prestage, per-partition reducers."""
    from ray_tpu.core.config import get_config

    refs = list(refs)
    if not refs:
        return refs
    n_out = len(refs)
    cfg = get_config()
    fanout = (cfg.data_stream_shuffle_fanout
              or cfg.transfer_broadcast_fanout)

    scatter = ray_tpu.remote(_scatter_bundle).options(num_returns=2)
    combine = ray_tpu.remote(_combine_part)

    ss = np.random.SeedSequence(seed)
    seeds = ss.generate_state(len(refs) + n_out)
    t0 = time.monotonic()
    bundles, sizes = [], []
    for i, r in enumerate(refs):
        b, s = scatter.remote(r, n_out, int(seeds[i]))
        bundles.append(b)
        sizes.append(s)
    # Bundles must be sealed before they can relay; the wait doubles as
    # the mapper barrier every all-to-all has anyway.
    ray_tpu.wait(bundles, num_returns=len(bundles))
    _prestage(bundles, fanout)
    out = [combine.remote(int(seeds[len(refs) + j]), j, *bundles)
           for j in range(n_out)]
    ray_tpu.wait(out, num_returns=len(out))
    elapsed = time.monotonic() - t0
    try:
        from ray_tpu.data.streaming import metrics as dm

        dm.on_shuffle(dataset, sum(ray_tpu.get(sizes)), elapsed)
    except Exception:  # noqa: BLE001
        pass
    return out
