"""Streaming data plane: backpressured Dataset execution over the
zero-copy transfer plane.

The package replaces the block-materializing default path in
``data/execution.py`` (kept as the ``RAY_TPU_DATA_STREAM_ENABLED=0``
fallback) with a byte-budgeted operator graph:

- ``executor``  — operator graph whose submissions are gated by a
  bytes-windowed backpressure budget (per-operator in-flight byte caps,
  stall accounting, spill fallback) instead of task counts alone.
- ``shuffle``   — all-to-all shuffle bundles ride the broadcast/relay
  trees and the range-serve path of the transfer plane instead of N²
  point-to-point pickled gets.
- ``split``     — ack-based streaming split coordinator that re-splits
  on elastic world-size change mid-epoch without dropping or
  duplicating samples.
- ``prefetch``  — pipeline-resident double-buffered host→HBM feed for
  ``iter_jax_batches`` (device_put of batch k+1 overlaps compute on k).
- ``metrics``   — per-operator data-plane gauges federated over the
  report-gauges → syncer → GCS path.
"""
from ray_tpu.data.streaming.executor import streaming_enabled, streaming_execute
from ray_tpu.data.streaming.prefetch import DevicePrefetcher
from ray_tpu.data.streaming.split import StreamSplitCoordinator

__all__ = [
    "DevicePrefetcher",
    "StreamSplitCoordinator",
    "streaming_enabled",
    "streaming_execute",
]
