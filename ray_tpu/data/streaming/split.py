"""Elastic streaming split: ack-based block handout that survives
world-size changes mid-epoch.

The legacy ``_SplitCoordinator`` (data/dataset.py) hands refs out
fire-and-forget: a consumer that dies between delivery and processing
silently loses its block, and a resize has no way to redistribute
queued work. This coordinator tracks one *outstanding* (delivered but
not yet acknowledged) block per consumer — requesting block k+1
acknowledges block k, matching the iterator's consume-then-request
discipline — so on ``resplit(new_n)`` or ``mark_dead(idx)`` the
unacknowledged blocks are requeued for the surviving consumers:

- no epoch restart — the single streaming execution keeps going
  (``epoch_id`` never changes across a resize);
- no lost samples — every unacked block goes back on the pending queue;
- no duplicates — acked blocks were fully consumed and are never
  replayed (the elastic supervisor re-invokes the shard fn only after
  the dead/stopped workers' last step committed).

(ref: python/ray/data/_internal/execution/operators/output_splitter.py
OutputSplitter — plus the Train elastic ingest semantics the reference
leaves to the caller.)
"""
from __future__ import annotations

import logging
from collections import deque
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger(__name__)


@ray_tpu.remote(num_cpus=0)
class StreamSplitCoordinator:
    """Hands one streaming execution's block refs to N consumers with
    per-consumer outstanding tracking and live re-splitting."""

    def __init__(self, dataset, n: int, equal: bool = False):
        self._n = n
        self._equal = equal
        self._it = iter(dataset.to_block_refs())
        self._queues: List[deque] = [deque() for _ in range(n)]
        self._pending: deque = deque()   # requeued (resplit / death)
        self._outstanding: Dict[int, Any] = {}
        self._next_rr = 0
        self._done = False
        self._epoch_id = 0          # never bumped by resize: one epoch
        self._delivered = 0
        self._acked = 0
        self._resplits = 0

    # -- source -----------------------------------------------------------
    def _pull(self):
        if self._pending:
            return self._pending.popleft()
        if self._done:
            return None
        try:
            return next(self._it)
        except StopIteration:
            self._done = True
            return self._pending.popleft() if self._pending else None

    def _exhausted(self) -> bool:
        return (self._done and not self._pending
                and not any(self._queues))

    # -- consumer protocol ------------------------------------------------
    def next_block(self, consumer_idx: int):
        """Next block ref for this consumer, or None when exhausted.
        Implicitly acks the consumer's previous block: the iterator
        only asks for k+1 after fully consuming k."""
        if consumer_idx in self._outstanding:
            self._outstanding.pop(consumer_idx)
            self._acked += 1
        if consumer_idx >= self._n:
            # Stale consumer from before a shrink: nothing for it.
            return None
        ref = None
        if self._equal:
            q = self._queues[consumer_idx]
            while not q and not self._exhausted():
                nxt = self._pull()
                if nxt is None:
                    break
                self._queues[self._next_rr].append(nxt)
                self._next_rr = (self._next_rr + 1) % self._n
            if q:
                ref = q.popleft()
        else:
            ref = self._pull()
        if ref is not None:
            self._outstanding[consumer_idx] = ref
            self._delivered += 1
        return ref

    def ack(self, consumer_idx: int) -> None:
        """Explicit ack (e.g. the train loop commits a step boundary
        before checkpointing); the implicit next_block ack covers the
        normal path."""
        if consumer_idx in self._outstanding:
            self._outstanding.pop(consumer_idx)
            self._acked += 1

    # -- elastic ----------------------------------------------------------
    def mark_dead(self, consumer_idx: int) -> None:
        """Requeue a killed consumer's unacked block so survivors get
        it (SIGKILL path: the block was delivered but never consumed)."""
        ref = self._outstanding.pop(consumer_idx, None)
        if ref is not None:
            self._pending.append(ref)
            logger.info("split consumer %d died with 1 outstanding "
                        "block; requeued", consumer_idx)

    def resplit(self, new_n: int) -> int:
        """Live world-size change: requeue every unacked/queued block
        and continue the SAME epoch with new_n consumers. Returns the
        new world size (for the caller's sanity check)."""
        for idx in list(self._outstanding):
            self._pending.append(self._outstanding.pop(idx))
        for q in self._queues:
            while q:
                self._pending.append(q.popleft())
        self._n = new_n
        self._queues = [deque() for _ in range(new_n)]
        self._next_rr = 0
        self._resplits += 1
        return new_n

    # -- introspection ----------------------------------------------------
    def progress(self) -> Dict[str, Any]:
        return {
            "epoch_id": self._epoch_id,
            "world": self._n,
            "delivered": self._delivered,
            "acked": self._acked,
            "outstanding": len(self._outstanding),
            "pending": len(self._pending),
            "resplits": self._resplits,
            "exhausted": self._exhausted(),
        }


class StreamingIngest:
    """Elastic train ingest over ONE streaming execution.

    Pass ``{"train": StreamingIngest(ds)}`` as a Trainer dataset: the
    trainer's shard fn calls :meth:`shard` on every gang formation, and
    a world-size change triggers ``resplit`` on the shared coordinator
    instead of re-executing the dataset — mid-epoch progress survives
    grow and shrink.  Pickles cleanly once the coordinator exists
    (actor handle + bookkeeping)."""

    def __init__(self, dataset, *, equal: bool = False,
                 block_timeout_s: Optional[float] = None):
        self._dataset = dataset
        self._equal = equal
        self._block_timeout_s = block_timeout_s
        self._coord = None
        self._world: Optional[int] = None

    @property
    def coordinator(self):
        return self._coord

    def shard(self, rank: int, world: int):
        from ray_tpu.data.dataset import StreamingSplitIterator

        if self._coord is None:
            self._coord = StreamSplitCoordinator.remote(
                self._dataset, world, self._equal)
            self._world = world
        elif world != self._world:
            ray_tpu.get(self._coord.resplit.remote(world))
            self._world = world
        return StreamingSplitIterator(self._coord, rank,
                                      self._block_timeout_s)

    # Trainer._shard_fn duck-types on split(); StreamingIngest is
    # handled explicitly there instead (needs rank AND world).
