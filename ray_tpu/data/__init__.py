"""ray_tpu.data: distributed data pipelines (reference: ray.data).

Arrow blocks in the shared-memory object store, lazy plans with map-stage
fusion, a byte-budgeted streaming executor over the zero-copy transfer
plane (data/streaming — backpressure windows, relay-tree shuffle,
elastic splits), and TPU device feeding (`Dataset.iter_jax_batches`
keeps device_put of batch k+1 overlapping compute on batch k).
"""
from ray_tpu.data.dataset import Dataset, GroupedData, from_block_list
from ray_tpu.data.streaming.split import StreamingIngest
from ray_tpu.data.read_api import (
    from_arrow, from_huggingface, from_items, from_numpy, from_pandas,
    from_torch, range, range_tensor, read_bigquery, read_binary_files,
    read_csv, read_images, read_json, read_mongo, read_numpy,
    read_parquet, read_sql, read_text, read_tfrecords, read_webdataset)

__all__ = [
    "Dataset", "GroupedData", "StreamingIngest", "from_block_list",
    "range", "range_tensor", "from_items", "from_numpy", "from_arrow",
    "from_pandas", "from_huggingface", "from_torch",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_images", "read_numpy", "read_tfrecords",
    "read_webdataset", "read_sql", "read_mongo", "read_bigquery",
]

# Usage tagging (ref: usage_lib.record_library_usage; local-only,
# see ray_tpu/util/usage_stats.py)
from ray_tpu.util.usage_stats import record_library_usage as _rlu

_rlu("data")
del _rlu
