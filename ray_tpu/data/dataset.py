"""Dataset: lazy, immutable, distributed collection of Arrow blocks.

Reference surface being reproduced (ref: python/ray/data/dataset.py:137 —
map_batches :371, iter_batches :3640, materialize :4520; grouped_data.py;
_internal/split.py).  Execution is deferred: transforms append stages to a
logical plan; consumption streams block refs through the executor.
"""
from __future__ import annotations

import functools
import itertools
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Union)

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data.execution import execute
from ray_tpu.data.plan import AllToAllStage, MapStage, ReadTask

BatchUDF = Callable[..., Any]


def _batch_map_blockfn(fn, batch_size, batch_format, fn_kwargs):
    def block_fn(block: B.Block) -> Iterable[B.Block]:
        for piece in B.batches(block, batch_size):
            out = fn(B.to_batch(piece, batch_format), **fn_kwargs)
            yield B.from_batch(out)

    return block_fn


def _row_map_blockfn(kind: str, fn):
    def block_fn(block: B.Block) -> Iterable[B.Block]:
        rows = list(B.iter_rows(block))
        if kind == "map":
            out = [fn(r) for r in rows]
        elif kind == "filter":
            out = [r for r in rows if fn(r)]
        else:  # flat_map
            out = list(itertools.chain.from_iterable(fn(r) for r in rows))
        if not out:
            yield block.slice(0, 0)
            return
        yield B.from_rows(out)

    return block_fn


def _rebatch(block_iter: Iterable[B.Block], batch_size: int,
             batch_format: Optional[str], drop_last: bool) -> Iterator[Any]:
    """Re-slice a block stream into fixed-size batches."""
    carry: Optional[B.Block] = None
    for blk in block_iter:
        if carry is not None and carry.num_rows:
            blk = B.concat([carry, blk])
            carry = None
        start = 0
        while blk.num_rows - start >= batch_size:
            yield B.to_batch(blk.slice(start, batch_size), batch_format)
            start += batch_size
        carry = blk.slice(start)
    if carry is not None and carry.num_rows and not drop_last:
        yield B.to_batch(carry, batch_format)


def _jax_feed(batch_iter: Iterator[dict], sharding, dtypes,
              prefetch: Optional[int], name: str) -> Iterator[Any]:
    """Shared device feed for Dataset / streaming-split iterators:
    dtype cast + device_put behind a DevicePrefetcher of the configured
    depth (RAY_TPU_DATA_STREAM_PREFETCH_DEPTH when `prefetch` is None)."""
    import jax

    from ray_tpu.core.config import get_config
    from ray_tpu.data.streaming.prefetch import device_prefetching

    def to_device(np_batch):
        if dtypes:
            np_batch = {k: v.astype(dtypes[k]) if k in dtypes else v
                        for k, v in np_batch.items()}
        if sharding is not None:
            return {k: jax.device_put(v, sharding)
                    for k, v in np_batch.items()}
        return {k: jax.device_put(v) for k, v in np_batch.items()}

    depth = (get_config().data_stream_prefetch_depth
             if prefetch is None else prefetch)
    yield from device_prefetching(batch_iter, to_device, depth=depth,
                                  name=name)


def _torch_batches(batch_iter: Iterator[dict]) -> Iterator[dict]:
    """numpy batches → torch tensors (copying read-only shm views;
    torch needs writable memory for in-place training ops)."""
    import torch

    for batch in batch_iter:
        yield {k: torch.as_tensor(
                   v if getattr(v, "flags", None) is None
                   or v.flags.writeable else np.array(v))
               for k, v in batch.items()}


class Dataset:
    def __init__(self, read_tasks: List[ReadTask], stages: List[Any] = None):
        self._read_tasks = read_tasks
        self._stages = stages or []

    # ---------------- transforms (lazy) ----------------
    def _with(self, stage) -> "Dataset":
        return Dataset(self._read_tasks, self._stages + [stage])

    def map_batches(self, fn: BatchUDF, *, batch_size: Optional[int] = None,
                    batch_format: Optional[str] = None,
                    compute: Optional[Any] = None, concurrency: int = 0,
                    fn_constructor_args: tuple = (),
                    fn_kwargs: Optional[dict] = None, **_ignored) -> "Dataset":
        """Apply a UDF per batch.  Class UDFs run on an actor pool
        (`concurrency` actors); function UDFs fuse into producer tasks."""
        fn_kwargs = fn_kwargs or {}
        if isinstance(fn, type):
            n = concurrency or (compute if isinstance(compute, int) else 2)

            def maker(cls=fn, args=fn_constructor_args, kw=dict(fn_kwargs),
                      bs=batch_size, bf=batch_format):
                inst = cls(*args)
                return _batch_map_blockfn(inst, bs, bf, kw)

            return self._with(MapStage(
                name=f"MapBatches({fn.__name__})",
                block_fn=None, actor_fn_maker=maker, num_actors=n))
        return self._with(MapStage(
            name=f"MapBatches({getattr(fn, '__name__', 'fn')})",
            block_fn=_batch_map_blockfn(fn, batch_size, batch_format,
                                        fn_kwargs)))

    def map(self, fn) -> "Dataset":
        return self._with(MapStage("Map", _row_map_blockfn("map", fn)))

    def filter(self, fn) -> "Dataset":
        return self._with(MapStage("Filter", _row_map_blockfn("filter", fn)))

    def flat_map(self, fn) -> "Dataset":
        return self._with(MapStage("FlatMap",
                                   _row_map_blockfn("flat_map", fn)))

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch

        return self.map_batches(add, batch_format="numpy")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda t: t.drop_columns(cols), batch_format="pyarrow")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda t: t.select(cols), batch_format="pyarrow")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(
            lambda t: t.rename_columns(
                [mapping.get(c, c) for c in t.column_names]),
            batch_format="pyarrow")

    def limit(self, n: int) -> "Dataset":
        # Streaming cutoff: pulls upstream refs only until n rows are seen,
        # so execution of the tail never happens.
        def ref_fn(ref_iter):
            def gen():
                left = n
                for ref in ref_iter:
                    if left <= 0:
                        break
                    blk = ray_tpu.get(ref)
                    take = min(left, blk.num_rows)
                    left -= take
                    yield (ref if take == blk.num_rows
                           else ray_tpu.put(blk.slice(0, take)))

            return gen()

        return self._with(AllToAllStage("Limit", ref_fn))

    # ---------------- all-to-all ----------------
    def repartition(self, num_blocks: int) -> "Dataset":
        def ref_fn(refs):
            refs = list(refs)
            if not refs:
                return refs
            blocks = ray_tpu.get(refs)
            whole = B.concat(blocks)
            n = whole.num_rows
            per = max(1, -(-n // num_blocks))
            return [ray_tpu.put(whole.slice(i * per, per))
                    for i in range(num_blocks) if i * per < n or n == 0]

        return self._with(AllToAllStage("Repartition", ref_fn))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Distributed map-reduce shuffle: each block scatters rows into
        num_blocks partitions; reducers concat+permute
        (ref: data/_internal shuffle — push-based variant not needed yet).

        On the streaming path every mapper packs its partitions into ONE
        offset-addressed bundle that rides the broadcast/relay trees
        (prestaged node-local on multi-node clusters) instead of N²
        point-to-point pickled gets — see data/streaming/shuffle.py."""
        def streaming_ref_fn(refs):
            from ray_tpu.data.streaming.shuffle import streaming_shuffle_refs

            return streaming_shuffle_refs(refs, seed, self._name())

        def ref_fn(refs):
            from ray_tpu.data.streaming import streaming_enabled

            if streaming_enabled():
                return streaming_ref_fn(refs)
            refs = list(refs)
            if not refs:
                return refs
            n_out = len(refs)

            @ray_tpu.remote
            def scatter(block, n, s):
                rng = np.random.default_rng(s)
                idx = rng.permutation(block.num_rows)
                parts = np.array_split(idx, n)
                out = tuple(block.take(pa.array(p)) for p in parts)
                return out[0] if n == 1 else out

            @ray_tpu.remote
            def combine(s, *parts):
                t = B.concat(list(parts))
                rng = np.random.default_rng(s)
                return t.take(pa.array(rng.permutation(t.num_rows)))

            ss = np.random.SeedSequence(seed)
            seeds = ss.generate_state(2 * len(refs) + n_out)
            scattered = [
                scatter.options(num_returns=n_out).remote(r, n_out,
                                                          int(seeds[i]))
                for i, r in enumerate(refs)]
            if n_out == 1:
                scattered = [[s] for s in scattered]
            return [combine.remote(int(seeds[len(refs) + j]),
                                   *[scattered[i][j]
                                     for i in range(len(refs))])
                    for j in range(n_out)]

        return self._with(AllToAllStage("RandomShuffle", ref_fn))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed range-partition sort (ref: _internal/planner/
        exchange/sort_task_spec.py:1 SortTaskSpec — sample → boundaries →
        per-block partition map → per-range merge). No task ever holds
        more than ~1/num_blocks of the data, so datasets larger than any
        single worker's memory sort fine (the previous one-task
        `sort_all` funneled everything through one worker)."""
        order = "descending" if descending else "ascending"

        def ref_fn(refs):
            refs = list(refs)
            if not refs:
                return refs
            n_out = len(refs)

            @ray_tpu.remote
            def sort_block(b):
                return b.sort_by([(key, order)])

            if n_out == 1:
                return [sort_block.remote(refs[0])]

            # 1) Sample boundary candidates from every block.
            @ray_tpu.remote
            def sample_keys(block, k=64):
                if block.num_rows == 0:
                    return None
                idx = np.linspace(0, block.num_rows - 1,
                                  min(k, block.num_rows)).astype(np.int64)
                return (block.column(key).take(pa.array(idx))
                        .to_numpy(zero_copy_only=False))

            samples = [s for s in ray_tpu.get(
                [sample_keys.remote(r) for r in refs]) if s is not None]
            if not samples:
                return refs
            allsamp = np.sort(np.concatenate(samples))
            cut_idx = np.linspace(0, allsamp.size - 1,
                                  n_out + 1).astype(np.int64)[1:-1]
            bounds = allsamp[cut_idx]

            # 2) Partition map: each block splits into n_out key ranges
            # (always ascending; descending flips the range order below).
            @ray_tpu.remote
            def partition(block, bnds, n):
                sb = block.sort_by([(key, "ascending")])
                keys = sb.column(key).to_numpy(zero_copy_only=False)
                cuts = np.searchsorted(keys, bnds, side="left")
                edges = [0, *cuts.tolist(), sb.num_rows]
                parts = tuple(sb.slice(edges[i], edges[i + 1] - edges[i])
                              for i in range(n))
                return parts[0] if n == 1 else parts

            # 3) Per-range merge: concat this range's shards + local sort.
            @ray_tpu.remote
            def merge(*parts):
                return B.concat(list(parts)).sort_by([(key, order)])

            parted = [partition.options(num_returns=n_out)
                      .remote(r, bounds, n_out) for r in refs]
            out = [merge.remote(*[parted[i][j] for i in range(len(refs))])
                   for j in range(n_out)]
            return out[::-1] if descending else out

        return self._with(AllToAllStage("Sort", ref_fn))

    def groupby(self, key) -> "GroupedData":
        """Group by one column or a LIST of columns (ref:
        python/ray/data/grouped_data.py multi-key groupby)."""
        return GroupedData(self, key)

    def union(self, other: "Dataset") -> "Dataset":
        if self._stages or other._stages:
            left = self.materialize()
            right = other.materialize()
            return Dataset(left._read_tasks + right._read_tasks)
        return Dataset(self._read_tasks + other._read_tasks)

    def zip(self, other: "Dataset") -> "Dataset":
        def ref_fn(refs):
            mine = B.concat(ray_tpu.get(list(refs)))
            theirs = B.concat(ray_tpu.get(list(other.to_block_refs())))
            n = min(mine.num_rows, theirs.num_rows)
            mine, theirs = mine.slice(0, n), theirs.slice(0, n)
            cols = {c: mine.column(c) for c in mine.column_names}
            for c in theirs.column_names:
                cols[c if c not in cols else f"{c}_1"] = theirs.column(c)
            return [ray_tpu.put(pa.table(cols))]

        return self._with(AllToAllStage("Zip", ref_fn))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        # Per-call entropy when unseeded; per-block entropy from a content
        # digest so equal-sized blocks don't draw identical masks.
        import secrets
        import zlib

        call_entropy = seed if seed is not None else secrets.randbits(63)

        def block_fn(block):
            digest = 0
            if block.num_columns and block.num_rows:
                for buf in block.column(0).combine_chunks().chunk(0).buffers():
                    if buf is not None:
                        digest = zlib.crc32(bytes(buf)[:4096], digest)
            rng = np.random.default_rng((call_entropy, digest,
                                         block.num_rows))
            mask = rng.random(block.num_rows) < fraction
            yield block.filter(pa.array(mask))

        return self._with(MapStage("RandomSample", block_fn))

    # ---------------- split ----------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Materialize and split into n datasets (ref: dataset.py split;
        used for per-host train shards)."""
        refs = list(self.to_block_refs())
        blocks = ray_tpu.get(refs)
        whole = B.concat(blocks)
        total = whole.num_rows
        per = total // n if equal else -(-total // n)
        out = []
        for i in range(n):
            start = min(i * per, total)
            end = min((i + 1) * per, total) if i < n - 1 or equal else total
            t = whole.slice(start, max(0, end - start))
            out.append(from_block_list([t]))
        return out

    def streaming_split(self, n: int, *, equal: bool = False
                        ) -> List["StreamingSplitIterator"]:
        """N per-consumer iterators over ONE streaming execution of this
        dataset (ref: _internal/execution/operators/output_splitter.py:1
        OutputSplitter + Dataset.streaming_split — the multi-worker Train
        ingest path). Blocks are handed out first-come-first-served by a
        coordinator actor, so fast consumers take more and slow ones
        never stall the pipeline; `equal=True` instead enforces
        round-robin handout (consumers advance in lockstep).

        On the streaming path the coordinator is the ack-based
        StreamSplitCoordinator (data/streaming/split.py): it tracks one
        outstanding block per consumer and supports live resplit() on
        elastic world-size change — no epoch restart, no lost or
        duplicated samples."""
        from ray_tpu.data.streaming import streaming_enabled

        if streaming_enabled():
            from ray_tpu.data.streaming.split import StreamSplitCoordinator

            coord = StreamSplitCoordinator.remote(self, n, equal)
        else:
            coord = _SplitCoordinator.remote(self, n, equal)
        return [StreamingSplitIterator(coord, i) for i in range(n)]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        whole = B.concat(ray_tpu.get(list(self.to_block_refs())))
        bounds = [0] + list(indices) + [whole.num_rows]
        return [from_block_list([whole.slice(a, b - a)])
                for a, b in zip(bounds[:-1], bounds[1:])]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        whole = B.concat(ray_tpu.get(list(ds.to_block_refs())))
        cut = int(whole.num_rows * (1 - test_size))
        return (from_block_list([whole.slice(0, cut)]),
                from_block_list([whole.slice(cut)]))

    # ---------------- execution / consumption ----------------
    def to_block_refs(self) -> Iterator[Any]:
        from ray_tpu.data.stats import DatasetStats
        from ray_tpu.data.streaming import streaming_enabled, streaming_execute

        self._last_stats = DatasetStats()
        if streaming_enabled():
            # Default path: byte-budgeted streaming operator graph over
            # the transfer plane (RAY_TPU_DATA_STREAM_ENABLED=0 falls
            # back to the legacy block-materializing executor).
            try:
                yield from streaming_execute(self._read_tasks, self._stages,
                                             stats=self._last_stats)
            finally:
                from ray_tpu.data.streaming import metrics as _dm

                _dm.on_execution(self._name(), self._last_stats)
            return
        yield from execute(self._read_tasks, self._stages,
                           stats=self._last_stats)

    def _name(self) -> str:
        return getattr(self, "_label", "ds")

    def iter_blocks(self) -> Iterator[B.Block]:
        for ref in self.to_block_refs():
            blk = ray_tpu.get(ref)
            stats = getattr(self, "_last_stats", None)
            if stats is not None:
                stats.consumed_rows += blk.num_rows
                stats.consumed_bytes += blk.nbytes
            yield blk

    def stats(self) -> str:
        """Execution stats of the most recent consumption (ref:
        Dataset.stats(), data/_internal/stats.py)."""
        stats = getattr(self, "_last_stats", None)
        if stats is None:
            return "Dataset has not been executed yet."
        return stats.summary()

    def materialize(self) -> "Dataset":
        refs = list(self.to_block_refs())
        return _materialized(refs)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: Optional[str] = None,
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        yield from _rebatch(self.iter_blocks(), batch_size, batch_format,
                            drop_last)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False) -> Iterator[dict]:
        """Batches as torch tensors (ref: Dataset.iter_torch_batches)."""
        yield from _torch_batches(self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last))

    def iter_rows(self) -> Iterator[Any]:
        for blk in self.iter_blocks():
            yield from B.iter_rows(blk)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_blocks())

    def sum(self, col: str):
        import pyarrow.compute as pc

        return sum(pc.sum(b.column(col)).as_py() or 0
                   for b in self.iter_blocks())

    def min(self, col: str):
        import pyarrow.compute as pc

        return min(pc.min(b.column(col)).as_py() for b in self.iter_blocks())

    def max(self, col: str):
        import pyarrow.compute as pc

        return max(pc.max(b.column(col)).as_py() for b in self.iter_blocks())

    def mean(self, col: str):
        total, cnt = 0.0, 0
        for b in self.iter_blocks():
            import pyarrow.compute as pc

            s = pc.sum(b.column(col)).as_py()
            total += s or 0
            cnt += b.num_rows
        return total / cnt if cnt else float("nan")

    def std(self, col: str, ddof: int = 1):
        """Streaming standard deviation (Chan parallel-variance merge
        across blocks — no global materialization)."""
        import pyarrow.compute as pc

        count, mean, m2 = 0, 0.0, 0.0
        for b in self.iter_blocks():
            # Weight by VALID values — nulls carry no mass (an all-null
            # block contributes nothing; pc.mean would return None).
            n = pc.count(b.column(col), mode="only_valid").as_py()
            if not n:
                continue
            bm = pc.mean(b.column(col)).as_py()
            bv = pc.variance(b.column(col), ddof=0).as_py() or 0.0
            delta = bm - mean
            total = count + n
            m2 += bv * n + delta * delta * count * n / total
            mean += delta * n / total
            count = total
        if count <= ddof:
            return float("nan")
        return float(np.sqrt(m2 / (count - ddof)))

    def quantile(self, col: str, q: float = 0.5):
        """Exact quantile; pulls only the ONE column to the driver."""
        import pyarrow.compute as pc

        chunks = [b.column(col) for b in self.iter_blocks()
                  if b.num_rows]
        if not chunks:
            return float("nan")
        combined = pa.chunked_array(chunks)
        return pc.quantile(combined, q=q).to_pylist()[0]

    def unique(self, col: str) -> List[Any]:
        """Distinct values of a column, streamed block by block."""
        import pyarrow.compute as pc

        seen: set = set()
        for b in self.iter_blocks():
            seen.update(pc.unique(b.column(col)).to_pylist())
        return sorted(seen, key=lambda v: (v is None, v))

    def schema(self) -> Optional[pa.Schema]:
        for b in self.iter_blocks():
            return b.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def num_blocks(self) -> int:
        return len(list(self.to_block_refs()))

    def size_bytes(self) -> int:
        return sum(b.nbytes for b in self.iter_blocks())

    def to_pandas(self):
        return B.concat(list(self.iter_blocks())).to_pandas()

    def to_arrow(self) -> pa.Table:
        return B.concat(list(self.iter_blocks()))

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return B.to_numpy(self.to_arrow())

    # ---------------- writes ----------------
    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def write_tfrecords(self, path: str) -> None:
        """One .tfrecords file per block, rows encoded as
        tf.train.Example via the built-in codec (ref: Dataset.
        write_tfrecords)."""
        import os

        from ray_tpu.data import tfrecord

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self.iter_blocks()):
            f = os.path.join(path, f"part-{i:05d}.tfrecords")
            tfrecord.write_records(
                f, (tfrecord.encode_example(row)
                    for row in B.iter_rows(blk)))

    def write_mongo(self, *, database: str, collection: str,
                    uri: Optional[str] = None,
                    client_factory=None) -> None:
        """Insert every row into a MongoDB collection (ref: datasource/
        mongo_datasource.py write path). `client_factory` is the same
        injectable seam as `read_mongo`. Blocks stream through the
        DRIVER sequentially — sink writes are correctness-first here;
        distribute by mapping a write over shards yourself if the sink
        is the bottleneck."""
        if client_factory is None:
            def client_factory():  # pragma: no cover - needs a mongod
                import pymongo

                return pymongo.MongoClient(uri)

        client = client_factory()
        try:
            coll = client[database][collection]
            for blk in self.iter_blocks():
                rows = [dict(r) for r in B.iter_rows(blk)]
                if rows:
                    coll.insert_many(rows)
        finally:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass

    def write_bigquery(self, *, dataset: str,
                       project_id: Optional[str] = None,
                       client_factory=None) -> None:
        """Load every block into a BigQuery table (ref: datasource/
        bigquery_datasource.py write path); `dataset` is
        "dataset.table"."""
        if client_factory is None:
            def client_factory():  # pragma: no cover - needs GCP creds
                from google.cloud import bigquery

                return bigquery.Client(project=project_id)

        client = client_factory()
        try:
            for blk in self.iter_blocks():
                job = client.load_table_from_dataframe(blk.to_pandas(),
                                                       dataset)
                job.result()
        finally:
            try:
                client.close()
            except Exception:  # noqa: BLE001 fakes without close()
                pass

    def _write(self, path: str, fmt: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self.iter_blocks()):
            f = os.path.join(path, f"part-{i:05d}.{fmt}")
            if fmt == "parquet":
                import pyarrow.parquet as pq

                pq.write_table(blk, f)
            elif fmt == "csv":
                import pyarrow.csv as pcsv

                pcsv.write_csv(blk, f)
            else:
                blk.to_pandas().to_json(f, orient="records", lines=True)

    # ---------------- device feeding (TPU-specific) ----------------
    def iter_jax_batches(self, *, batch_size: int, sharding=None,
                         dtypes: Optional[dict] = None, drop_last: bool = True,
                         prefetch: Optional[int] = None) -> Iterator[Any]:
        """Pipeline-resident host→HBM feed: a background thread owns
        batch formation + `jax.device_put` and keeps up to `prefetch`
        device-resident batches parked, so the transfer of batch k+1
        overlaps compute on batch k (double buffering at the default
        depth; see data/streaming/prefetch.py)."""
        yield from _jax_feed(
            self.iter_batches(batch_size=batch_size, batch_format="numpy",
                              drop_last=drop_last),
            sharding, dtypes, prefetch, self._name())

    def __repr__(self):
        names = [getattr(s, "name", "?") for s in self._stages]
        return (f"Dataset(blocks~{len(self._read_tasks)}, "
                f"stages={names})")


@ray_tpu.remote(num_cpus=0)
class _SplitCoordinator:
    """Hands one streaming execution's block refs out to N consumers
    (ref: output_splitter.py OutputSplitter). Lives in an actor so every
    consumer — typically a Train worker on another node — pulls from the
    SAME execution instead of re-executing the dataset per shard."""

    def __init__(self, dataset, n: int, equal: bool):
        self._n = n
        self._equal = equal
        self._it = iter(dataset.to_block_refs())
        self._queues: List[list] = [[] for _ in range(n)]
        self._next_rr = 0
        self._done = False

    def _pull(self):
        try:
            return next(self._it)
        except StopIteration:
            self._done = True
            return None

    def next_block(self, consumer_idx: int):
        """Next block ref for this consumer, or None when exhausted."""
        if not self._equal:
            return None if self._done else self._pull()
        q = self._queues[consumer_idx]
        while not q and not self._done:
            ref = self._pull()
            if ref is None:
                break
            self._queues[self._next_rr].append(ref)
            self._next_rr = (self._next_rr + 1) % self._n
        return q.pop(0) if q else None


class StreamingSplitIterator:
    """One consumer's shard of a streaming_split (ref: DataIterator,
    python/ray/data/iterator.py — the object handed to each Train
    worker). Pickles cleanly (actor handle + index), single pass.

    `block_timeout_s` bounds each next_block wait (None = wait forever,
    the default: the FIRST block legitimately waits on the whole
    upstream pipeline — an AllToAll barrier, autoscaler provisioning)."""

    def __init__(self, coord, idx: int,
                 block_timeout_s: Optional[float] = None):
        self._coord = coord
        self._idx = idx
        self._block_timeout_s = block_timeout_s

    def iter_blocks(self) -> Iterator[B.Block]:
        while True:
            ref = ray_tpu.get(self._coord.next_block.remote(self._idx),
                              timeout=self._block_timeout_s)
            if ref is None:
                return
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: Optional[str] = None,
                     drop_last: bool = False) -> Iterator[Any]:
        yield from _rebatch(self.iter_blocks(), batch_size, batch_format,
                            drop_last)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False) -> Iterator[dict]:
        yield from _torch_batches(self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last))

    def iter_jax_batches(self, *, batch_size: int, sharding=None,
                         dtypes: Optional[dict] = None,
                         drop_last: bool = True,
                         prefetch: Optional[int] = None) -> Iterator[Any]:
        """Device-prefetched shard feed: the train-worker counterpart of
        Dataset.iter_jax_batches, so each elastic shard keeps device_put
        of batch k+1 overlapping compute on batch k."""
        yield from _jax_feed(
            self.iter_batches(batch_size=batch_size, batch_format="numpy",
                              drop_last=drop_last),
            sharding, dtypes, prefetch, f"split-{self._idx}")

    def iter_rows(self) -> Iterator[Any]:
        for blk in self.iter_blocks():
            yield from B.iter_rows(blk)


class GroupedData:
    """Groupby-aggregate over one or many key columns (ref:
    python/ray/data/grouped_data.py — multi-key groupby, named
    aggregations, map_groups)."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._keys: List[str] = [key] if isinstance(key, str) else \
            list(key)
        if not self._keys:
            raise ValueError("groupby needs at least one key column")

    def _agg(self, aggs: List[tuple]) -> Dataset:
        keys = self._keys

        def ref_fn(refs):
            refs = list(refs)

            @ray_tpu.remote
            def agg_all(*blocks):
                import pyarrow.compute as pc

                # Options ride as ("OptionsClassName", kwargs) specs —
                # pyarrow FunctionOptions instances don't pickle.
                real = [
                    (a[0], a[1], getattr(pc, a[2][0])(**a[2][1]))
                    if len(a) == 3 and isinstance(a[2], tuple) else a
                    for a in aggs
                ]
                t = B.concat(list(blocks))
                tbl = t.group_by(keys).aggregate(real)
                # pyarrow names output "<col>_<fn>"; keep as-is
                return tbl.sort_by([(k, "ascending") for k in keys])

            return [agg_all.remote(*refs)]

        return self._ds._with(AllToAllStage("GroupByAgg", ref_fn))

    def aggregate(self, *aggs: tuple) -> Dataset:
        """Named aggregations: (col, fn) pairs with any pyarrow
        group-by function — 'sum', 'mean', 'min', 'max', 'count',
        'stddev', 'variance', 'count_distinct', ... — or
        (col, fn, ("OptionsClassName", kwargs)) triples for pyarrow
        FunctionOptions, e.g. ("v", "stddev", ("VarianceOptions",
        {"ddof": 1})) — specs, because FunctionOptions instances don't
        pickle across workers. Multiple at once produce one row per
        group with a column per aggregate."""
        if not aggs:
            raise ValueError("aggregate() needs (col, fn) pairs")
        return self._agg(list(aggs))

    def count(self) -> Dataset:
        return self._agg([(self._keys[0], "count")])

    def sum(self, col: str) -> Dataset:
        return self._agg([(col, "sum")])

    def mean(self, col: str) -> Dataset:
        return self._agg([(col, "mean")])

    def min(self, col: str) -> Dataset:
        return self._agg([(col, "min")])

    def max(self, col: str) -> Dataset:
        return self._agg([(col, "max")])

    def std(self, col: str, ddof: int = 1) -> Dataset:
        # pyarrow's grouped stddev defaults to ddof=0; match
        # Dataset.std's sample-std default explicitly.
        return self._agg([(col, "stddev",
                           ("VarianceOptions", {"ddof": ddof}))])

    def map_groups(self, fn, *, batch_format: Optional[str] = None) -> Dataset:
        keys = self._keys

        def ref_fn(refs):
            refs = list(refs)

            @ray_tpu.remote
            def apply(*blocks):
                import pyarrow.compute as pc

                t = B.concat(list(blocks))
                # Distinct key combos via an empty aggregation, then
                # one conjunctive filter per group.
                combos = t.group_by(keys).aggregate([])
                outs = []
                for i in range(combos.num_rows):
                    mask = None
                    for k in keys:
                        m = pc.equal(t.column(k), combos.column(k)[i])
                        mask = m if mask is None else pc.and_(mask, m)
                    grp = t.filter(mask)
                    res = fn(B.to_batch(grp, batch_format))
                    outs.append(B.from_batch(res))
                return B.concat(outs)

            return [apply.remote(*refs)]

        return self._ds._with(AllToAllStage("MapGroups", ref_fn))


def _materialized(refs: List[Any]) -> Dataset:
    tasks = [ReadTask(fn=functools.partial(ray_tpu.get, r), name="cached")
             for r in refs]
    return Dataset(tasks)


def from_block_list(blocks: List[B.Block]) -> Dataset:
    refs = [ray_tpu.put(b) for b in blocks]
    return _materialized(refs)
