"""Block model: a Dataset is a list of Arrow-table blocks in the object store.

Mirrors the reference's block design (ref: python/ray/data/block.py — blocks
are Arrow/pandas tables held in plasma, workers exchange ObjectRefs).  Here
a block is always a `pyarrow.Table`; batches handed to UDFs are converted
to the requested `batch_format` ("numpy" dict, "pandas", "pyarrow").
Tensors ride as fixed-shape-list columns and convert to stacked ndarrays.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np
import pyarrow as pa

Block = pa.Table

TENSOR_META_KEY = b"rtpu_tensor_shape"


def _np_to_column(arr: np.ndarray):
    """ndarray column → Arrow.  >1-D arrays become FixedSizeList columns."""
    if arr.ndim <= 1:
        return pa.array(arr)
    flat = arr.reshape(len(arr), -1)
    inner = pa.array(flat.ravel())
    return pa.FixedSizeListArray.from_arrays(inner, flat.shape[1])


def from_batch(batch: Any) -> Block:
    """Build a block from a UDF return: dict-of-ndarray, pandas, or table."""
    import pandas as pd

    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, pd.DataFrame):
        return pa.Table.from_pandas(batch, preserve_index=False)
    if isinstance(batch, dict):
        names, cols, meta = [], [], {}
        for k, v in batch.items():
            v = np.asarray(v)
            names.append(k)
            cols.append(_np_to_column(v))
            if v.ndim > 1:
                meta[f"{k}.shape"] = ",".join(map(str, v.shape[1:]))
        t = pa.table(dict(zip(names, cols)))
        if meta:
            t = t.replace_schema_metadata(
                {TENSOR_META_KEY: json.dumps(meta).encode()})
        return t
    raise TypeError(f"cannot build a block from {type(batch).__name__}")


def from_rows(rows: List[Any]) -> Block:
    """Items → single-column block ('item') or struct columns for dicts."""
    if rows and isinstance(rows[0], dict):
        keys = list(rows[0].keys())
        return pa.table({k: [r[k] for r in rows] for k in keys})
    return pa.table({"item": list(rows)})


def _tensor_shapes(block: Block) -> Dict[str, tuple]:
    # Schema metadata survives round-trips through external files
    # (read_parquet preserves it), so it is attacker-controlled input:
    # strict JSON only, never eval.
    meta = (block.schema.metadata or {}).get(TENSOR_META_KEY)
    if not meta:
        return {}
    try:
        d = json.loads(meta.decode())
    except (ValueError, UnicodeDecodeError):
        import logging

        logging.getLogger(__name__).warning(
            "unparseable %s metadata (%.60r...): tensor columns will come "
            "back flat", TENSOR_META_KEY.decode(), meta)
        return {}
    if not isinstance(d, dict):
        return {}
    out = {}
    for k, v in d.items():
        try:
            out[str(k).rsplit(".shape", 1)[0]] = tuple(
                int(x) for x in str(v).split(","))
        except ValueError:
            continue
    return out


def to_numpy(block: Block) -> Dict[str, np.ndarray]:
    shapes = _tensor_shapes(block)
    out = {}
    for name in block.column_names:
        col = block.column(name)
        if pa.types.is_fixed_size_list(col.type):
            w = col.type.list_size
            flat = col.combine_chunks().flatten().to_numpy(zero_copy_only=False)
            arr = flat.reshape(len(block), w)
            if name in shapes:
                arr = arr.reshape((len(block),) + shapes[name])
            out[name] = arr
        else:
            out[name] = col.to_numpy(zero_copy_only=False)
    return out


def to_pandas(block: Block):
    return block.to_pandas()


def to_batch(block: Block, batch_format: Optional[str]):
    if batch_format in (None, "numpy", "np"):
        return to_numpy(block)
    if batch_format in ("pandas", "pd"):
        return to_pandas(block)
    if batch_format in ("pyarrow", "arrow"):
        return block
    raise ValueError(f"unknown batch_format {batch_format!r}")


def iter_rows(block: Block) -> Iterator[Dict[str, Any]]:
    cols = to_numpy(block)
    names = list(cols)
    for i in range(len(block)):
        row = {k: cols[k][i] for k in names}
        yield row["item"] if names == ["item"] else row


def slice_block(block: Block, start: int, end: int) -> Block:
    return block.slice(start, end - start)


def concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b is not None and b.num_rows > 0]
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks, promote_options="default")


def batches(block: Block, batch_size: Optional[int]) -> Iterator[Block]:
    if batch_size is None or batch_size >= block.num_rows:
        if block.num_rows:
            yield block
        return
    for s in range(0, block.num_rows, batch_size):
        yield block.slice(s, batch_size)


def size_bytes(block: Block) -> int:
    return block.nbytes


def num_rows(block: Block) -> int:
    return block.num_rows
