"""Per-execution dataset statistics.

Analogue of the reference's DatasetStats (ref: python/ray/data/
_internal/stats.py — per-operator wall time/task counts surfaced by
`ds.stats()` after an execution). Collected driver-side by the streaming
executor; consumption counters (rows/bytes) fill in as blocks are
actually fetched by the iterating caller.
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional


@dataclasses.dataclass
class StageStats:
    name: str
    tasks: int = 0
    first_submit: Optional[float] = None
    last_output: Optional[float] = None
    peak_queue: int = 0       # max observed operator input-queue depth
    peak_in_flight: int = 0   # max concurrently running tasks
    # -- streaming-executor byte accounting (data/streaming) --
    rows_out: int = 0         # rows produced by this operator
    bytes_out: int = 0        # bytes produced (sealed block sizes)
    stall_s: float = 0.0      # seconds submission was byte-backpressured
    peak_inflight_bytes: int = 0  # max produced-but-unconsumed bytes
    spilled_tasks: int = 0    # over-budget submissions via spill fallback

    def on_submit(self) -> None:
        self.tasks += 1
        if self.first_submit is None:
            self.first_submit = time.monotonic()

    def on_output(self, rows: int = 0, nbytes: int = 0) -> None:
        self.last_output = time.monotonic()
        self.rows_out += rows
        self.bytes_out += nbytes

    def on_stall(self, seconds: float) -> None:
        self.stall_s += seconds

    def on_inflight_bytes(self, n: int) -> None:
        self.peak_inflight_bytes = max(self.peak_inflight_bytes, n)

    def on_queue(self, depth: int) -> None:
        self.peak_queue = max(self.peak_queue, depth)

    def on_active(self, n: int) -> None:
        self.peak_in_flight = max(self.peak_in_flight, n)

    @property
    def wall_s(self) -> float:
        if self.first_submit is None or self.last_output is None:
            return 0.0
        return self.last_output - self.first_submit

    def overlaps(self, other: "StageStats") -> bool:
        """True when the two stages' execution windows intersect —
        the observable signature of pipelined operators."""
        if None in (self.first_submit, self.last_output,
                    other.first_submit, other.last_output):
            return False
        return (self.first_submit < other.last_output
                and other.first_submit < self.last_output)


class DatasetStats:
    def __init__(self):
        self.stages: List[StageStats] = []
        self.consumed_rows = 0
        self.consumed_bytes = 0
        self.started = time.monotonic()

    def new_stage(self, name: str) -> StageStats:
        st = StageStats(name)
        self.stages.append(st)
        return st

    def summary(self) -> str:
        lines = ["Dataset execution stats:"]
        for st in self.stages:
            line = (
                f"  {st.name}: {st.tasks} tasks, {st.wall_s * 1000:.0f} ms"
                f" wall, peak in-flight {st.peak_in_flight}, "
                f"peak queue {st.peak_queue}")
            if st.bytes_out or st.stall_s or st.rows_out:
                line += (
                    f", {st.rows_out} rows / "
                    f"{st.bytes_out / 1e6:.2f} MB out, "
                    f"stalled {st.stall_s * 1000:.0f} ms")
                if st.spilled_tasks:
                    line += f", spilled {st.spilled_tasks} tasks"
            lines.append(line)
        lines.append(
            f"  consumed: {self.consumed_rows} rows, "
            f"{self.consumed_bytes / 1e6:.2f} MB")
        return "\n".join(lines)
