"""Per-execution dataset statistics.

Analogue of the reference's DatasetStats (ref: python/ray/data/
_internal/stats.py — per-operator wall time/task counts surfaced by
`ds.stats()` after an execution). Collected driver-side by the streaming
executor; consumption counters (rows/bytes) fill in as blocks are
actually fetched by the iterating caller.
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional


@dataclasses.dataclass
class StageStats:
    name: str
    tasks: int = 0
    first_submit: Optional[float] = None
    last_output: Optional[float] = None

    def on_submit(self) -> None:
        self.tasks += 1
        if self.first_submit is None:
            self.first_submit = time.monotonic()

    def on_output(self) -> None:
        self.last_output = time.monotonic()

    @property
    def wall_s(self) -> float:
        if self.first_submit is None or self.last_output is None:
            return 0.0
        return self.last_output - self.first_submit


class DatasetStats:
    def __init__(self):
        self.stages: List[StageStats] = []
        self.consumed_rows = 0
        self.consumed_bytes = 0
        self.started = time.monotonic()

    def new_stage(self, name: str) -> StageStats:
        st = StageStats(name)
        self.stages.append(st)
        return st

    def summary(self) -> str:
        lines = ["Dataset execution stats:"]
        for st in self.stages:
            lines.append(
                f"  {st.name}: {st.tasks} tasks, {st.wall_s * 1000:.0f} ms"
                f" wall")
        lines.append(
            f"  consumed: {self.consumed_rows} rows, "
            f"{self.consumed_bytes / 1e6:.2f} MB")
        return "\n".join(lines)
