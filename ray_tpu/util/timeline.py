"""Chrome-trace timeline export from the GCS task-event sink.

ref: `ray timeline` (python/ray/_private/state.py:917 chrome_tracing_dump
over profile events, _private/profiling.py). Open the output in
chrome://tracing or https://ui.perfetto.dev.

One merged trace: task status transitions (submit slice on the caller's
row, run slice on the worker's row, joined by a flow arrow), tracing
spans (on the emitting node/worker rows), and opt-in profile events
(object transfers etc.) — all in the same process/thread grid so a
task's whole life reads left-to-right across rows.
"""
from __future__ import annotations

import json
from typing import List, Optional


def fetch_task_events(limit: int = 10000) -> List[dict]:
    from ray_tpu.api import _global_worker

    return _global_worker().gcs.call("TaskEvents", "list_events",
                                     limit=limit, timeout=30)


def _node_row(node_id) -> str:
    return f"node:{(node_id or '?')[:8]}"


def chrome_trace(events: Optional[List[dict]] = None) -> List[dict]:
    """Convert task events to chrome-trace events: 'X' (complete) slices
    plus 's'/'f' flow arrows from each attempt's submit slice to its run
    slice."""
    if events is None:
        events = fetch_task_events()
    trace: List[dict] = []
    flow_seq = 0
    for e in events:
        kind = e.get("kind")
        if kind == "span":
            from ray_tpu.util.tracing import spans_to_chrome_trace

            trace.extend(spans_to_chrome_trace([e]))
            continue
        if kind == "profile":
            start, end = e.get("start_ts"), e.get("end_ts")
            if start is None or end is None:
                continue
            row = _node_row(e.get("node_id"))
            trace.append({
                "name": e.get("name", "profile"),
                "cat": e.get("category", "profile"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(0.0, end - start) * 1e6,
                "pid": row,
                "tid": f"worker:{e.get('pid', '?')}",
                "args": {k: v for k, v in e.items()
                         if k not in ("kind", "name", "category",
                                      "start_ts", "end_ts")},
            })
            if e.get("samples") is not None:
                # Counter track: `ray-tpu profile` captures annotate
                # the node row with their sample weight, so a capture
                # window reads as a labelled spike next to the tasks
                # it sampled (perfetto renders 'C' events as tracks).
                counter = {"name": "cpu_profile_samples",
                           "cat": "cpu_profile", "ph": "C", "pid": row}
                trace.append({**counter, "ts": start * 1e6,
                              "args": {"samples": e["samples"]}})
                trace.append({**counter, "ts": end * 1e6,
                              "args": {"samples": 0}})
            continue
        name = e.get("name", "task")
        st = e.get("state_ts") or {}
        run_start = e.get("start_ts") or st.get("RUNNING")
        end = e.get("end_ts")
        args = {
            "task_id": e.get("task_id"),
            "state": e.get("state"),
            "attempt": e.get("attempt"),
            "error": e.get("error"),
            "state_ts": st,
        }
        run_row = None
        if run_start is not None and end is not None:
            run_row = (_node_row(e.get("node_id")),
                       f"worker:{e.get('pid', '?')}")
            trace.append({
                "name": name,
                "cat": "actor_task" if e.get("actor_id") else "task",
                "ph": "X",
                "ts": run_start * 1e6,
                "dur": max(0.0, end - run_start) * 1e6,
                "pid": run_row[0],
                "tid": run_row[1],
                "args": args,
            })
        submit_ts = st.get("SUBMITTED")
        if submit_ts is not None:
            # Submit slice on the CALLER's row, spanning submission to
            # lease/run handoff (floored so perfetto renders it).
            handoff = st.get("LEASED") or run_start
            sub_row = (_node_row(e.get("submit_node_id")),
                       f"driver:{e.get('submit_pid', '?')}")
            trace.append({
                "name": f"submit:{name}",
                "cat": "submit",
                "ph": "X",
                "ts": submit_ts * 1e6,
                "dur": max(1.0, ((handoff or submit_ts) - submit_ts)
                           * 1e6),
                "pid": sub_row[0],
                "tid": sub_row[1],
                "args": args,
            })
            if run_row is not None and run_start >= submit_ts:
                # Flow arrow: submit -> run. Same id binds the pair; the
                # 's' sits inside the submit slice, the 'f' at the run
                # slice's start (bp=e attaches to the enclosing slice).
                flow_seq += 1
                fid = (f"{e.get('task_id', flow_seq)}:"
                       f"{e.get('attempt', 0)}")
                flow = {"name": "submit_to_run", "cat": "task_flow",
                        "id": fid}
                trace.append({**flow, "ph": "s", "ts": submit_ts * 1e6,
                              "pid": sub_row[0], "tid": sub_row[1]})
                trace.append({**flow, "ph": "f", "bp": "e",
                              "ts": run_start * 1e6,
                              "pid": run_row[0], "tid": run_row[1]})
    return trace


def timeline(filename: str = "timeline.json") -> str:
    """Dump the cluster's task timeline as a chrome trace; returns path."""
    with open(filename, "w") as f:
        json.dump(chrome_trace(), f)
    return filename


# ---------------------------------------------------------------------------
# Per-request serve traces (`ray-tpu serve trace <request-id>`): the
# request id IS the trace id, so one trace_id filter over the GCS span
# sink yields the request's whole serving path — proxy admission, handle
# routing (and failover re-routes), replica hop, engine queue_wait /
# prefill chunks / per-burst decode, stream batches.
# ---------------------------------------------------------------------------

def fetch_spans(trace_id: Optional[str] = None,
                limit: int = 10000) -> List[dict]:
    from ray_tpu.api import _global_worker

    return _global_worker().gcs.call("TaskEvents", "list_spans",
                                     trace_id=trace_id, limit=limit,
                                     timeout=30)


def request_chrome_trace(spans: List[dict]) -> List[dict]:
    """Chrome-trace events for ONE request: a dedicated
    `request:<id>` process whose threads are the serving hops, so the
    track reads top-to-bottom in causal order (proxy -> handle ->
    replica -> engine) and left-to-right in time.  Hop = the span-name
    segment after "serve." ("proxy.request" -> "proxy"); resumed spans
    render in their own `<hop> (resumed)` rows so a failover shows as a
    visible second act on the same track."""
    out: List[dict] = []
    hop_order = {"proxy": 0, "handle": 1, "replica": 2, "engine": 3}
    for s in spans:
        if s.get("end_ts") is None or s.get("start_ts") is None:
            continue
        parts = s.get("name", "").split(".")
        hop = parts[1] if len(parts) > 1 and parts[0] == "serve" \
            else parts[0] or "span"
        attrs = s.get("attrs", {}) or {}
        tid = f"{hop_order.get(hop, 9)}:{hop}"
        if attrs.get("resumed"):
            tid += " (resumed)"
        out.append({
            "name": s.get("name", "span"),
            "cat": "serve_request",
            "ph": "X",
            "ts": s["start_ts"] * 1e6,
            "dur": max(1.0, (s["end_ts"] - s["start_ts"]) * 1e6),
            "pid": f"request:{(s.get('trace_id') or '?')[:12]}",
            "tid": tid,
            "args": {**attrs,
                     "trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "parent_id": s.get("parent_id"),
                     "node_id": s.get("node_id"),
                     "pid": s.get("pid")},
        })
    return out


def request_trace(request_id: str,
                  filename: Optional[str] = None) -> str:
    """Dump one request's serving-path spans as a chrome trace; returns
    the path (default `trace-<first 12 of id>.json`)."""
    spans = fetch_spans(trace_id=request_id)
    if not spans:
        raise ValueError(
            f"no spans recorded for request {request_id!r} (is "
            f"RAY_TPU_SERVE_TRACE_ENABLED=0, or has the span buffer "
            f"not flushed yet?)")
    if filename is None:
        filename = f"trace-{request_id[:12]}.json"
    with open(filename, "w") as f:
        json.dump(request_chrome_trace(spans), f)
    return filename


# ---------------------------------------------------------------------------
# Per-run train traces (`ray-tpu train trace <run>`): the run id
# (experiment name + fit attempt, e.g. "mnist#0") IS the trace id.
# Stable across gang restarts within a fit, so a chaos run's failover
# leg renders in the same trace as the attempt it replaced.
# ---------------------------------------------------------------------------

def train_chrome_trace(spans: List[dict]) -> List[dict]:
    """Chrome-trace events for ONE training run: a dedicated
    `run:<id>` process with one thread PER RANK, so cross-rank skew is
    visible as ragged step edges down the rank rows.  `train.step`
    spans carry the per-phase attribution in args; `phase.*` child
    spans nest inside their step slice on the same rank row.  A gang
    restart's new attempt renders on `rank N (attempt K)` rows — the
    visible second act of a failover."""
    out: List[dict] = []
    for s in spans:
        if s.get("end_ts") is None or s.get("start_ts") is None:
            continue
        attrs = s.get("attrs", {}) or {}
        rank = attrs.get("rank", "?")
        attempt = attrs.get("attempt", 0)
        tid = f"{rank:>04}:rank {rank}" if isinstance(rank, int) \
            else f"zzzz:rank {rank}"
        if attempt:
            tid += f" (attempt {attempt})"
        out.append({
            "name": s.get("name", "span"),
            "cat": "train_run",
            "ph": "X",
            "ts": s["start_ts"] * 1e6,
            "dur": max(1.0, (s["end_ts"] - s["start_ts"]) * 1e6),
            "pid": f"run:{s.get('trace_id') or '?'}",
            "tid": tid,
            "args": {**attrs,
                     "trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "parent_id": s.get("parent_id"),
                     "node_id": s.get("node_id"),
                     "pid": s.get("pid")},
        })
    return out


def train_trace(run_id: str, filename: Optional[str] = None) -> str:
    """Dump one training run's per-rank step/phase spans as a chrome
    trace; returns the path (default `train-trace-<run>.json`)."""
    spans = fetch_spans(trace_id=run_id)
    if not spans and "#" not in run_id:
        # Bare experiment name: take every fit attempt of it
        # ("mnist" matches "mnist#0", "mnist#1", ...).
        spans = [s for s in fetch_spans()
                 if (s.get("trace_id") or "").startswith(f"{run_id}#")]
    if not spans:
        raise ValueError(
            f"no spans recorded for train run {run_id!r} (is "
            f"RAY_TPU_TRAIN_OBS_ENABLED=0, or has the span buffer "
            f"not flushed yet?)")
    if filename is None:
        filename = f"train-trace-{run_id.replace('#', '_')}.json"
    with open(filename, "w") as f:
        json.dump(train_chrome_trace(spans), f)
    return filename
