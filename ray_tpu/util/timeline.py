"""Chrome-trace timeline export from the GCS task-event sink.

ref: `ray timeline` (python/ray/_private/state.py:917 chrome_tracing_dump
over profile events, _private/profiling.py). Open the output in
chrome://tracing or https://ui.perfetto.dev.
"""
from __future__ import annotations

import json
from typing import List, Optional


def fetch_task_events(limit: int = 10000) -> List[dict]:
    from ray_tpu.api import _global_worker

    return _global_worker().gcs.call("TaskEvents", "list_events",
                                     limit=limit, timeout=30)


def chrome_trace(events: Optional[List[dict]] = None) -> List[dict]:
    """Convert task events to chrome-trace 'X' (complete) events."""
    if events is None:
        events = fetch_task_events()
    trace = []
    for e in events:
        if e.get("kind") == "span":
            from ray_tpu.util.tracing import spans_to_chrome_trace

            trace.extend(spans_to_chrome_trace([e]))
            continue
        start, end = e.get("start_ts"), e.get("end_ts")
        if start is None or end is None:
            continue
        trace.append({
            "name": e.get("name", "task"),
            "cat": "actor_task" if e.get("actor_id") else "task",
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(0.0, (end - start)) * 1e6,
            "pid": f"node:{(e.get('node_id') or '?')[:8]}",
            "tid": f"worker:{e.get('pid', '?')}",
            "args": {
                "task_id": e.get("task_id"),
                "state": e.get("state"),
                "attempt": e.get("attempt"),
                "error": e.get("error"),
            },
        })
    return trace


def timeline(filename: str = "timeline.json") -> str:
    """Dump the cluster's task timeline as a chrome trace; returns path."""
    with open(filename, "w") as f:
        json.dump(chrome_trace(), f)
    return filename
