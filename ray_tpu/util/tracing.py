"""Distributed tracing: spans with cross-task context propagation.

Analogue of the reference's OpenTelemetry tracing hooks
(ref: python/ray/util/tracing/tracing_helper.py — _OpenTelemetryProxy
:34, `_DictPropagator` :165 injecting the span context into the
TaskSpec, extracted around task execution in _raylet.pyx). Here the span
model is self-contained (no opentelemetry dependency in a zero-egress
image): spans carry trace_id/span_id/parent_id, the current context
propagates via a contextvar, `inject()/extract()` move it through task
specs, and finished spans flush into the GCS TaskEvents sink (kind
"span") so `ray-tpu timeline` renders traces next to task rows. An
OTLP-shaped exporter can be plugged via `set_exporter`.

Opt-in: RAY_TPU_TRACING_ENABLED=1 (ref: ray.init(_tracing_startup_hook)).
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core.config import get_config

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "ray_tpu_span", default=None)
_buffer: List[dict] = []
_buffer_lock = threading.Lock()
_exporter: Optional[Callable[[List[dict]], None]] = None
MAX_BUFFER = 10000
# Which node this process runs on (set by the core worker at init):
# stamped onto finished spans so the timeline can place them under the
# emitting node/worker rows instead of a synthetic trace_id process.
_node_id: Optional[str] = None


def set_node_context(node_id: str) -> None:
    global _node_id
    _node_id = node_id


def enabled() -> bool:
    return get_config().tracing_enabled


def serve_enabled() -> bool:
    """Serving-plane request tracing (independent of the generic task
    tracing opt-in; RAY_TPU_SERVE_TRACE_ENABLED=0 is the kill switch)."""
    return get_config().serve_trace_enabled


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "start", "end")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs or {}
        self.start = time.time()
        self.end: Optional[float] = None

    def finish(self, end_ts: Optional[float] = None) -> dict:
        import os

        self.end = time.time() if end_ts is None else end_ts
        record = {
            "kind": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ts": self.start,
            "end_ts": self.end,
            "node_id": _node_id,
            "pid": os.getpid(),
            "attrs": self.attrs,
        }
        with _buffer_lock:
            _buffer.append(record)
            if len(_buffer) > MAX_BUFFER:
                del _buffer[:MAX_BUFFER // 2]
        return record


@contextlib.contextmanager
def span(name: str, **attrs):
    """Open a span under the current context (no-op when tracing is
    off). Usage: `with tracing.span("preprocess", rows=n): ...`"""
    if not enabled():
        yield None
        return
    parent = _current.get()
    s = Span(name,
             trace_id=(parent.trace_id if parent else uuid.uuid4().hex),
             parent_id=(parent.span_id if parent else None),
             attrs=attrs)
    token = _current.set(s)
    try:
        yield s
    finally:
        _current.reset(token)
        s.finish()


def inject() -> Optional[Dict[str, str]]:
    """Serialize the current span context for a TaskSpec (ref:
    _DictPropagator.inject_current_context)."""
    if not enabled():
        return None
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur.trace_id, "span_id": cur.span_id}


@contextlib.contextmanager
def extract_and_span(ctx: Optional[Dict[str, str]], name: str, **attrs):
    """Open an execution-side span whose parent is the submitted
    context (ref: the execute-side wrapper in _raylet.pyx)."""
    if not enabled() or ctx is None:
        yield None
        return
    s = Span(name, trace_id=ctx["trace_id"],
             parent_id=ctx.get("span_id"), attrs=attrs)
    token = _current.set(s)
    try:
        yield s
    finally:
        _current.reset(token)
        s.finish()


# ---------------------------------------------------------------------------
# Serving-plane request traces: the serve path passes an EXPLICIT context
# dict ({"trace_id": <request id>, "span_id": <parent>}) from hop to hop
# (proxy -> handle -> replica -> engine) instead of relying on the
# contextvar — the engine emits spans from its own tick thread, replicas
# from puller threads, none of which inherit the request's context.  The
# request id IS the trace id, so `ray-tpu serve trace <request-id>`
# is a trace_id filter over the GCS span sink.
# ---------------------------------------------------------------------------

def serve_ctx(request_id: str, parent_span_id: Optional[str] = None,
              **extra) -> Optional[Dict[str, Any]]:
    """Mint a serve trace context from a request id; None when serve
    tracing is off (every downstream helper no-ops on None)."""
    if not serve_enabled() or not request_id:
        return None
    ctx: Dict[str, Any] = {"trace_id": request_id,
                           "span_id": parent_span_id}
    ctx.update(extra)
    return ctx


def child_ctx(ctx: Optional[Dict[str, Any]],
              span: Optional["Span"]) -> Optional[Dict[str, Any]]:
    """Context for the next hop: same trace, parented under `span`."""
    if ctx is None:
        return None
    if span is None:
        return ctx
    out = dict(ctx)
    out["span_id"] = span.span_id
    return out


@contextlib.contextmanager
def serve_span(ctx: Optional[Dict[str, Any]], name: str, **attrs):
    """Open a serve-plane span under an explicit request context.
    No-op (yields None) when tracing is off or there is no context —
    the caller never branches."""
    if ctx is None or not serve_enabled():
        yield None
        return
    if ctx.get("resumed"):
        attrs.setdefault("resumed", 1)
    s = Span(name, trace_id=ctx["trace_id"],
             parent_id=ctx.get("span_id"), attrs=attrs)
    try:
        yield s
    finally:
        s.finish()


def record_serve_span(ctx: Optional[Dict[str, Any]], name: str,
                      start_ts: float, end_ts: Optional[float] = None,
                      **attrs) -> None:
    """Record an already-timed serve span (engine ticks measure their
    own wall window; spans are minted after the fact)."""
    if ctx is None or not serve_enabled():
        return
    if ctx.get("resumed"):
        attrs.setdefault("resumed", 1)
    s = Span(name, trace_id=ctx["trace_id"],
             parent_id=ctx.get("span_id"), attrs=attrs)
    s.start = start_ts
    s.finish(end_ts)


def train_enabled() -> bool:
    """Train-plane step/phase tracing — shares the
    RAY_TPU_TRAIN_OBS_ENABLED kill switch with the rest of the train
    observability stack (gauges, TrainRunState)."""
    return get_config().train_obs_enabled


def record_train_span(run_id: Optional[str], name: str, start_ts: float,
                      end_ts: Optional[float] = None,
                      parent_id: Optional[str] = None,
                      **attrs) -> Optional[str]:
    """Record an already-timed train-plane span. The run id IS the
    trace id (experiment name + fit attempt), so `ray-tpu train trace
    <run>` is a trace_id filter over the GCS span sink — the same query
    shape as serve request traces. Step loops measure their own wall
    windows, so spans are minted after the fact; returns the span id so
    phase children can parent under their step."""
    if not run_id or not train_enabled():
        return None
    s = Span(name, trace_id=run_id, parent_id=parent_id, attrs=attrs)
    s.start = start_ts
    s.finish(end_ts)
    return s.span_id


def drain() -> List[dict]:
    """Take all finished spans (the worker's event flusher ships them to
    the GCS TaskEvents sink)."""
    global _buffer
    with _buffer_lock:
        out, _buffer = _buffer, []
    if _exporter is not None and out:
        try:
            _exporter(out)
        except Exception:  # noqa: BLE001 exporter must not break flushing
            pass
    return out


def has_pending() -> bool:
    """Cheap liveness probe for the flush loop's idle backoff: a parked
    worker that suddenly mints spans (e.g. lands a restarted train gang)
    must wake within one flush period, not sit out a backed-off sleep."""
    return bool(_buffer)


def set_exporter(fn: Optional[Callable[[List[dict]], None]]) -> None:
    """Install an exporter invoked with each drained span batch (e.g. an
    OTLP forwarder); pass None to remove."""
    global _exporter
    _exporter = fn


def spans_to_chrome_trace(spans: List[dict]) -> List[dict]:
    """Chrome-tracing events for `ray-tpu timeline` merging. Spans land
    under the emitting node/worker rows (pid=node, tid=worker pid), the
    same rows their task slices render on — NOT under a synthetic
    pid=trace_id process, which scattered every trace into its own
    process group and never lined up with the task rows in perfetto.
    The trace/span lineage stays available in args."""
    out = []
    for s in spans:
        node = s.get("node_id")
        out.append({
            "name": s["name"],
            "cat": "span",
            "ph": "X",
            "ts": s["start_ts"] * 1e6,
            "dur": (s["end_ts"] - s["start_ts"]) * 1e6,
            "pid": f"node:{node[:8]}" if node else "node:?",
            "tid": f"worker:{s.get('pid', '?')}",
            "args": {**s.get("attrs", {}),
                     "trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "parent_id": s.get("parent_id")},
        })
    return out
