"""Metrics: Counter/Gauge/Histogram + process registry + Prometheus text.

User-facing API mirrors the reference (ref: python/ray/util/metrics.py:19
Counter, :137 Gauge/Histogram); the process-wide registry and text
exposition replace the reference's OpenCensus->metrics-agent->Prometheus
pipeline (ref: src/ray/stats/metric_defs.cc) with a single in-process
registry each daemon/worker exposes directly.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

_TagKey = Tuple[Tuple[str, str], ...]


def _tagkey(tags: Optional[Dict[str, str]]) -> _TagKey:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name.replace("_", "a").replace(":", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        get_registry().register(self)

    def _share_state(self, other: "Metric") -> None:
        """Alias this instance's sample storage onto `other`'s (registry
        name-collision adoption): both instances observe into ONE sample
        set while keeping their own default tags."""
        raise NotImplementedError

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> _TagKey:
        if self._default_tags:
            merged = dict(self._default_tags)
            merged.update(tags or {})
            return _tagkey(merged)
        return _tagkey(tags)

    def key(self, tags: Optional[Dict[str, str]] = None) -> _TagKey:
        """Precompute a sample key for the *_key fast paths: hot sites
        (the RPC transport observes ~10 samples per round trip) resolve
        tags once per (service, method) instead of building + sorting a
        dict per observation."""
        return self._merged(tags)

    # exposition
    def kind(self) -> str:
        raise NotImplementedError

    def samples(self) -> List[Tuple[_TagKey, float]]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing value (ref: util/metrics.py:19)."""

    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = defaultdict(float)
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc() takes a non-negative value")
        with self._lock:
            self._values[self._merged(tags)] += value

    def inc_key(self, key: _TagKey, value: float = 1.0) -> None:
        with self._lock:
            self._values[key] += value

    def _share_state(self, other: "Counter") -> None:
        self._values = other._values
        self._lock = other._lock

    def kind(self) -> str:
        return "counter"

    def samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge(Metric):
    """Last-set value (ref: util/metrics.py Gauge)."""

    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._merged(tags)] = float(value)

    def inc(self, value: float = 1.0, tags=None) -> None:
        with self._lock:
            k = self._merged(tags)
            self._values[k] = self._values.get(k, 0.0) + value

    def dec(self, value: float = 1.0, tags=None) -> None:
        self.inc(-value, tags)

    def inc_key(self, key: _TagKey, value: float = 1.0) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def track(self, value: float = 1.0, tags=None):
        """Context manager: add `value` for the duration of a block —
        the in-flight-bytes / in-flight-requests idiom (the transfer
        plane's windowed pulls account their outstanding chunk bytes
        this way, so the gauge can never leak on an exception path)."""
        return _GaugeTrack(self, value, tags)

    def remove(self, tags: Optional[Dict[str, str]] = None) -> None:
        """Drop one labelset's sample entirely (a gauge mirroring an
        external entity — e.g. a serve replica that aged out — must stop
        exporting it, not pin the last value forever)."""
        with self._lock:
            self._values.pop(self._merged(tags), None)

    def _share_state(self, other: "Gauge") -> None:
        self._values = other._values
        self._lock = other._lock

    def kind(self) -> str:
        return "gauge"

    def samples(self):
        with self._lock:
            return list(self._values.items())


class _GaugeTrack:
    __slots__ = ("_gauge", "_value", "_tags")

    def __init__(self, gauge: "Gauge", value: float, tags):
        self._gauge = gauge
        self._value = value
        self._tags = tags

    def __enter__(self):
        self._gauge.inc(self._value, self._tags)
        return self

    def __exit__(self, *exc):
        self._gauge.dec(self._value, self._tags)
        return False


class Histogram(Metric):
    """Bucketed observations (ref: util/metrics.py Histogram)."""

    # Sub-millisecond floor: the default consumer is RPC/event-loop
    # latency (a loopback unary round-trips in ~50µs), where the old
    # 1ms-floor default collapsed the entire control-plane fast path
    # into one bucket.
    DEFAULT_BOUNDARIES = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                          0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
                          10, 60)

    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        if not boundaries:
            boundaries = self.DEFAULT_BOUNDARIES
        self.boundaries = tuple(sorted(boundaries))
        self._counts: Dict[_TagKey, List[int]] = {}
        self._sums: Dict[_TagKey, float] = defaultdict(float)
        self._totals: Dict[_TagKey, int] = defaultdict(int)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        self.observe_key(self._merged(tags), value)

    def observe_key(self, key: _TagKey, value: float) -> None:
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.boundaries) + 1)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def time(self, tags: Optional[Dict[str, str]] = None):
        """Context manager observing the block's wall duration in
        seconds — the idiom for every RPC/handler latency site:
        `with hist.time({"method": m}): ...` can't leak an observation
        on an exception path."""
        return _HistogramTimer(self, tags)

    def _share_state(self, other: "Histogram") -> None:
        self._counts = other._counts
        self._sums = other._sums
        self._totals = other._totals
        self._lock = other._lock

    def kind(self) -> str:
        return "histogram"

    def samples(self):
        # Flattened as cumulative-bucket samples in prometheus_text().
        with self._lock:
            return [(k, float(t)) for k, t in self._totals.items()]

    def snapshot(self):
        with self._lock:
            return ({k: list(v) for k, v in self._counts.items()},
                    dict(self._sums), dict(self._totals))


class _HistogramTimer:
    __slots__ = ("_hist", "_tags", "_t0")

    def __init__(self, hist: "Histogram", tags):
        self._hist = hist
        self._tags = tags

    def __enter__(self):
        import time as _time

        self._t0 = _time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time as _time

        self._hist.observe(_time.perf_counter() - self._t0, self._tags)
        return False


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        """Register, adopting on name collision: a second instance with
        the same name/kind/tag_keys (and boundaries, for histograms)
        shares the existing instance's sample storage instead of
        silently orphaning it — in-process daemon restarts (virtual_node
        tests, InProcDaemonCluster) re-create every metric, and the old
        replace-on-register dropped all prior samples from exposition.
        A shape mismatch is a bug and raises."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is None or existing is metric:
                self._metrics[metric.name] = metric
                return
            if (existing.kind() != metric.kind()
                    or existing.tag_keys != metric.tag_keys
                    or getattr(existing, "boundaries", None)
                    != getattr(metric, "boundaries", None)):
                raise ValueError(
                    f"metric {metric.name!r} re-registered with a "
                    f"different shape: {existing.kind()}"
                    f"{existing.tag_keys} vs {metric.kind()}"
                    f"{metric.tag_keys}")
            metric._share_state(existing)

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def snapshot_meta(self) -> List[dict]:
        """Metadata of every registered metric (name/description/kind)
        — the input the Grafana dashboard factory renders panels from."""
        with self._lock:
            metrics = list(self._metrics.values())
        return [{"name": m.name, "description": m.description,
                 "kind": m.kind()} for m in metrics]

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain; version 0.0.4)."""
        out: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            desc = m.description.replace("\\", "\\\\").replace("\n", "\\n")
            out.append(f"# HELP {m.name} {desc}")
            out.append(f"# TYPE {m.name} {m.kind()}")
            if isinstance(m, Histogram):
                counts, sums, totals = m.snapshot()
                for key, buckets in counts.items():
                    base = _fmt_tags(key)
                    cum = 0
                    for b, c in zip(m.boundaries, buckets):
                        cum += c
                        out.append(
                            f"{m.name}_bucket{_fmt_tags(key, le=b)} {cum}")
                    cum += buckets[-1]
                    out.append(
                        f"{m.name}_bucket{_fmt_tags(key, le='+Inf')} {cum}")
                    out.append(f"{m.name}_sum{base} {sums[key]}")
                    out.append(f"{m.name}_count{base} {totals[key]}")
            else:
                for key, value in m.samples():
                    out.append(f"{m.name}{_fmt_tags(key)} {value}")
        return "\n".join(out) + "\n"


def _esc(value: str) -> str:
    """Escape per the Prometheus exposition format: \\, \", newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_tags(key: _TagKey, le=None) -> str:
    parts = [f'{k}="{_esc(v)}"' for k, v in key]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an unsorted sequence (q in [0, 100]);
    0.0 for an empty input. Used by the task-summary resource rollups —
    small windows (<=10k per job) make exact sorting fine."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
    return float(s[idx])


def registry_snapshot() -> List[dict]:
    return get_registry().snapshot_meta()


# ---------------------------------------------------------------------------
# Federation: structured per-process dumps the syncer ships to the GCS,
# merged there into one cluster-wide exposition (the analogue of
# Prometheus federation's instance-labelled scrape union).
# ---------------------------------------------------------------------------

def registry_dump() -> List[dict]:
    """Serializable snapshot of every metric WITH its samples (metadata
    + values; contrast snapshot_meta, which is metadata only). The shape
    survives the pickle RPC codec: plain dicts/lists/tuples."""
    reg = get_registry()
    with reg._lock:
        metrics = list(reg._metrics.values())
    out: List[dict] = []
    for m in metrics:
        rec = {"name": m.name, "description": m.description,
               "kind": m.kind()}
        if isinstance(m, Histogram):
            counts, sums, totals = m.snapshot()
            rec["boundaries"] = list(m.boundaries)
            rec["hist"] = [
                [list(key), list(buckets), sums[key], totals[key]]
                for key, buckets in counts.items()]
        else:
            rec["samples"] = [[list(key), value]
                              for key, value in m.samples()]
        out.append(rec)
    return out


def merge_dump_lists(dumps: Sequence[List[dict]]) -> List[dict]:
    """Merge several registry_dump() lists into ONE dump (the node
    daemon folds worker-pushed dumps — serve replicas, the HTTP proxy —
    into its own federation payload, so one node still ships one dump).
    Counters and histograms with identical (name, labelset) SUM (two
    replicas of one app on a node yield one per-app series); gauges are
    last-write-wins (distinguish them with labels — replica serve
    gauges carry app/replica tags).  Shape mismatches keep the first
    record seen."""
    merged: Dict[str, dict] = {}
    for dump in dumps:
        for rec in dump:
            name = rec.get("name")
            cur = merged.get(name)
            if cur is None:
                cur = {"name": name,
                       "description": rec.get("description", ""),
                       "kind": rec.get("kind")}
                if rec.get("kind") == "histogram":
                    cur["boundaries"] = list(rec.get("boundaries", []))
                    cur["hist"] = []
                else:
                    cur["samples"] = []
                merged[name] = cur
            if cur["kind"] != rec.get("kind"):
                continue
            if cur["kind"] == "histogram":
                if cur["boundaries"] != list(rec.get("boundaries", [])):
                    continue
                by_key = {tuple(map(tuple, h[0])): h for h in cur["hist"]}
                for key, buckets, hsum, total in rec.get("hist", []):
                    k = tuple(map(tuple, key))
                    have = by_key.get(k)
                    if have is None:
                        row = [list(key), list(buckets), hsum, total]
                        cur["hist"].append(row)
                        by_key[k] = row
                    else:
                        have[1] = [a + b for a, b in zip(have[1], buckets)]
                        have[2] += hsum
                        have[3] += total
            else:
                summing = cur["kind"] == "counter"
                by_key = {tuple(map(tuple, s[0])): s
                          for s in cur["samples"]}
                for key, value in rec.get("samples", []):
                    k = tuple(map(tuple, key))
                    have = by_key.get(k)
                    if have is None:
                        row = [list(key), value]
                        cur["samples"].append(row)
                        by_key[k] = row
                    elif summing:
                        have[1] += value
                    else:
                        have[1] = value
    return list(merged.values())


def merge_dumps(dumps: Dict[str, List[dict]]) -> str:
    """Render {origin -> registry_dump()} as ONE Prometheus exposition.
    Every sample gains a `node="<origin>"` label (federation's
    instance label), so identical tag sets from different processes —
    e.g. raytpu_rpc_handler_seconds{service=...,method=...} on every
    daemon — stay distinguishable instead of colliding."""
    meta: Dict[str, tuple] = {}          # name -> (kind, description)
    lines_by_name: Dict[str, List[str]] = {}
    for origin, dump in sorted(dumps.items()):
        for rec in dump:
            name = rec["name"]
            meta.setdefault(name, (rec["kind"], rec["description"]))
            out = lines_by_name.setdefault(name, [])
            if rec["kind"] == "histogram":
                bounds = rec.get("boundaries", [])
                for key, buckets, hsum, total in rec.get("hist", []):
                    key = _with_node(key, origin)
                    base = _fmt_tags(key)
                    cum = 0
                    for b, c in zip(bounds, buckets):
                        cum += c
                        out.append(
                            f"{name}_bucket{_fmt_tags(key, le=b)} {cum}")
                    if buckets:
                        cum += buckets[-1]
                    out.append(
                        f"{name}_bucket{_fmt_tags(key, le='+Inf')} {cum}")
                    out.append(f"{name}_sum{base} {hsum}")
                    out.append(f"{name}_count{base} {total}")
            else:
                for key, value in rec.get("samples", []):
                    out.append(
                        f"{name}{_fmt_tags(_with_node(key, origin))} "
                        f"{value}")
    text: List[str] = []
    for name in sorted(meta):
        kind, desc = meta[name]
        desc = desc.replace("\\", "\\\\").replace("\n", "\\n")
        text.append(f"# HELP {name} {desc}")
        text.append(f"# TYPE {name} {kind}")
        text.extend(lines_by_name[name])
    return "\n".join(text) + "\n"


def _with_node(key, origin: str) -> _TagKey:
    items = [(str(k), str(v)) for k, v in key if k != "node"]
    items.append(("node", origin))
    return tuple(sorted(items))


def process_sample() -> Dict[str, float]:
    """Best-effort self-metrics for the calling process: RSS bytes,
    cumulative CPU seconds, open fds, live threads.  Linux /proc is the
    primary source; getrusage is the portable fallback (its ru_maxrss
    is a high-water mark, not current RSS — still the right order of
    magnitude for a leak alarm).  Used by the GCS audit loop so the
    control plane's own footprint shows up node-labelled in the
    federated exposition alongside every daemon it monitors."""
    import os
    import resource

    out: Dict[str, float] = {}
    ru = resource.getrusage(resource.RUSAGE_SELF)
    out["cpu_seconds"] = ru.ru_utime + ru.ru_stime
    out["rss_bytes"] = float(ru.ru_maxrss) * 1024.0
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        out["rss_bytes"] = float(rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        out["open_fds"] = float(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass
    out["threads"] = float(threading.active_count())
    return out


_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry
