"""Metrics: Counter/Gauge/Histogram + process registry + Prometheus text.

User-facing API mirrors the reference (ref: python/ray/util/metrics.py:19
Counter, :137 Gauge/Histogram); the process-wide registry and text
exposition replace the reference's OpenCensus->metrics-agent->Prometheus
pipeline (ref: src/ray/stats/metric_defs.cc) with a single in-process
registry each daemon/worker exposes directly.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

_TagKey = Tuple[Tuple[str, str], ...]


def _tagkey(tags: Optional[Dict[str, str]]) -> _TagKey:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name.replace("_", "a").replace(":", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        get_registry().register(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> _TagKey:
        if self._default_tags:
            merged = dict(self._default_tags)
            merged.update(tags or {})
            return _tagkey(merged)
        return _tagkey(tags)

    # exposition
    def kind(self) -> str:
        raise NotImplementedError

    def samples(self) -> List[Tuple[_TagKey, float]]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing value (ref: util/metrics.py:19)."""

    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = defaultdict(float)
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc() takes a non-negative value")
        with self._lock:
            self._values[self._merged(tags)] += value

    def kind(self) -> str:
        return "counter"

    def samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge(Metric):
    """Last-set value (ref: util/metrics.py Gauge)."""

    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._merged(tags)] = float(value)

    def inc(self, value: float = 1.0, tags=None) -> None:
        with self._lock:
            k = self._merged(tags)
            self._values[k] = self._values.get(k, 0.0) + value

    def dec(self, value: float = 1.0, tags=None) -> None:
        self.inc(-value, tags)

    def track(self, value: float = 1.0, tags=None):
        """Context manager: add `value` for the duration of a block —
        the in-flight-bytes / in-flight-requests idiom (the transfer
        plane's windowed pulls account their outstanding chunk bytes
        this way, so the gauge can never leak on an exception path)."""
        return _GaugeTrack(self, value, tags)

    def kind(self) -> str:
        return "gauge"

    def samples(self):
        with self._lock:
            return list(self._values.items())


class _GaugeTrack:
    __slots__ = ("_gauge", "_value", "_tags")

    def __init__(self, gauge: "Gauge", value: float, tags):
        self._gauge = gauge
        self._value = value
        self._tags = tags

    def __enter__(self):
        self._gauge.inc(self._value, self._tags)
        return self

    def __exit__(self, *exc):
        self._gauge.dec(self._value, self._tags)
        return False


class Histogram(Metric):
    """Bucketed observations (ref: util/metrics.py Histogram)."""

    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        if not boundaries:
            boundaries = (0.001, 0.01, 0.1, 1, 10, 100, 1000)
        self.boundaries = tuple(sorted(boundaries))
        self._counts: Dict[_TagKey, List[int]] = {}
        self._sums: Dict[_TagKey, float] = defaultdict(float)
        self._totals: Dict[_TagKey, int] = defaultdict(int)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._merged(tags)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.boundaries) + 1)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def kind(self) -> str:
        return "histogram"

    def samples(self):
        # Flattened as cumulative-bucket samples in prometheus_text().
        with self._lock:
            return [(k, float(t)) for k, t in self._totals.items()]

    def snapshot(self):
        with self._lock:
            return ({k: list(v) for k, v in self._counts.items()},
                    dict(self._sums), dict(self._totals))


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def snapshot_meta(self) -> List[dict]:
        """Metadata of every registered metric (name/description/kind)
        — the input the Grafana dashboard factory renders panels from."""
        with self._lock:
            metrics = list(self._metrics.values())
        return [{"name": m.name, "description": m.description,
                 "kind": m.kind()} for m in metrics]

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain; version 0.0.4)."""
        out: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            desc = m.description.replace("\\", "\\\\").replace("\n", "\\n")
            out.append(f"# HELP {m.name} {desc}")
            out.append(f"# TYPE {m.name} {m.kind()}")
            if isinstance(m, Histogram):
                counts, sums, totals = m.snapshot()
                for key, buckets in counts.items():
                    base = _fmt_tags(key)
                    cum = 0
                    for b, c in zip(m.boundaries, buckets):
                        cum += c
                        out.append(
                            f"{m.name}_bucket{_fmt_tags(key, le=b)} {cum}")
                    cum += buckets[-1]
                    out.append(
                        f"{m.name}_bucket{_fmt_tags(key, le='+Inf')} {cum}")
                    out.append(f"{m.name}_sum{base} {sums[key]}")
                    out.append(f"{m.name}_count{base} {totals[key]}")
            else:
                for key, value in m.samples():
                    out.append(f"{m.name}{_fmt_tags(key)} {value}")
        return "\n".join(out) + "\n"


def _esc(value: str) -> str:
    """Escape per the Prometheus exposition format: \\, \", newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_tags(key: _TagKey, le=None) -> str:
    parts = [f'{k}="{_esc(v)}"' for k, v in key]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def registry_snapshot() -> List[dict]:
    return get_registry().snapshot_meta()


_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry
