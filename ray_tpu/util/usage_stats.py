"""Usage stats: what a cluster runs, recorded locally, reported only
on explicit opt-in.

ref: python/ray/_private/usage/usage_lib.py — the reference collects
cluster metadata + library-usage tags and (opt-out) reports them.
Divergences here: collection is in-memory + local-file only, and
REPORTING IS OPT-IN (RAY_TPU_USAGE_STATS_ENABLED=1 AND an explicit
report URL) — this framework targets air-gapped TPU pods where
silent egress is a bug, not a default.
"""
from __future__ import annotations

import json
import os
import platform
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_library_usages: set = set()
_extra_tags: Dict[str, str] = {}
_start_time = time.time()


def usage_stats_enabled() -> bool:
    from ray_tpu.core.config import get_config

    return bool(get_config().usage_stats_enabled)


def record_library_usage(library: str) -> None:
    """Tag that a library (data/train/tune/serve/rllib/...) was used
    in this process (ref: usage_lib.record_library_usage)."""
    with _lock:
        _library_usages.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    with _lock:
        _extra_tags[str(key)] = str(value)


def get_library_usages() -> List[str]:
    with _lock:
        return sorted(_library_usages)


def _get_extra_tags() -> Dict[str, str]:
    with _lock:
        return dict(_extra_tags)


def collect_usage_snapshot() -> Dict[str, Any]:
    """Everything a report would contain — inspectable by the user
    BEFORE anything leaves the machine."""
    from ray_tpu import _version

    snap: Dict[str, Any] = {
        "schema_version": 1,
        "ray_tpu_version": getattr(_version, "version", "unknown"),
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "uptime_s": round(time.time() - _start_time, 1),
        "libraries_used": get_library_usages(),
        "extra_tags": _get_extra_tags(),
    }
    try:
        import ray_tpu

        if ray_tpu.is_initialized():
            # Dead nodes keep their last-known resources in nodes();
            # counting them would double-book capacity.
            nodes = [n for n in ray_tpu.nodes() if n.get("Alive")]
            snap["num_nodes"] = len(nodes)
            total: Dict[str, float] = {}
            for n in nodes:
                for k, v in (n.get("Resources") or {}).items():
                    total[k] = total.get(k, 0.0) + float(v)
            snap["cluster_resources"] = {
                k: v for k, v in sorted(total.items())
                if not k.startswith("node:")}
    except Exception:  # noqa: BLE001 — snapshot must never fail
        pass
    return snap


def write_usage_snapshot(path: str) -> str:
    """Persist the snapshot locally (the reference writes
    usage_stats.json into the session dir)."""
    snap = collect_usage_snapshot()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=2)
    os.replace(tmp, path)
    return path


def report_usage(url: Optional[str] = None,
                 timeout_s: float = 10.0) -> bool:
    """POST the snapshot to `url` (or RAY_TPU_USAGE_STATS_URL) —
    ONLY when usage stats are explicitly enabled. Returns whether a
    report was sent; failures are swallowed (reporting must never
    break a workload, same rule as the reference)."""
    if not usage_stats_enabled():
        return False
    from ray_tpu.core.config import get_config

    url = url or get_config().usage_stats_url or None
    if not url:
        return False
    try:
        import urllib.request

        body = json.dumps(collect_usage_snapshot()).encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s):
            pass
        return True
    except Exception:  # noqa: BLE001
        return False
