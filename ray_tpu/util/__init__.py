from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
    placement_group_table,
)

__all__ = [
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "placement_group_table",
]
