"""Host-level (out-of-graph) collectives.

Reference: `ray.util.collective` — GroupManager (ref: python/ray/util/
collective/collective.py:40), init_collective_group :120, allreduce :258,
reducescatter :472, send/recv :531,594, NCCL/GLOO backends with a KV-store
rendezvous (ref: collective_group/nccl_collective_group.py:28 Rendezvous).

TPU-native split: the bandwidth-critical collectives live *inside* XLA
programs (ICI); this module is the control-plane/DCN path — CPU arrays
between hosts (gradient-of-metadata, rendezvous, eval aggregation).  The
transport is the GCS KV store: rank r publishes its contribution under
(group, seq, op, r), peers poll-read.  O(N²) bytes — right trade for small
host payloads; in-graph collectives handle the big ones.
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

_groups: Dict[str, "CollectiveGroup"] = {}
_lock = threading.Lock()


def _kv():
    from ray_tpu.api import _global_worker

    return _global_worker()


class CollectiveGroup:
    def __init__(self, name: str, world_size: int, rank: int,
                 incarnation: int = 0):
        from collections import defaultdict

        self.name = name
        self.world_size = world_size
        self.rank = rank
        # Distinguishes restarted groups: a rerun with the same group name
        # MUST bump `incarnation` (or use a fresh name) or it would read the
        # previous run's payloads.  Keys embed it.
        self.incarnation = incarnation
        # Per-op-kind sequence numbers: ranks must issue the same sequence
        # of *collective* ops (standard contract), while p2p pairs advance
        # independently of collectives and of other pairs.
        self._seqs = defaultdict(int)
        # Per-op GC watermark: lowest seq whose payload is not yet reclaimed.
        self._gc_marks: Dict[str, int] = {}

    def _next_seq(self, op: str) -> int:
        s = self._seqs[op]
        self._seqs[op] += 1
        return s

    # -- kv plumbing ----------------------------------------------------
    def _key(self, op: str, seq: int, rank: int) -> bytes:
        return (f"coll/{self.name}/i{self.incarnation}/{seq}/{op}/{rank}"
                .encode())

    def _put(self, op: str, seq: int, rank: int, payload: Any) -> None:
        _kv().kv_put(b"collective", self._key(op, seq, rank),
                     pickle.dumps(payload))

    def _get(self, op: str, seq: int, rank: int, timeout: float) -> Any:
        key = self._key(op, seq, rank)
        deadline = time.monotonic() + timeout
        delay = 0.001
        while True:
            blob = _kv().kv_get(b"collective", key)
            if blob is not None:
                return pickle.loads(blob)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective {op} seq={seq} rank={rank} timed out")
            time.sleep(delay)
            delay = min(delay * 2, 0.05)

    def _gather_all(self, op: str, value: Any, timeout: float) -> List[Any]:
        seq = self._next_seq(op)
        self._put(op, seq, self.rank, value)
        out = [self._get(op, seq, r, timeout)
               for r in range(self.world_size)]
        # Lazy GC — sound ONLY for gather-style ops, where issuing seq s
        # proves the issuer finished reading s-1: having read all seq-s
        # keys, every peer must have published s, hence finished reading
        # s-1, so deleting our own s-1 key is safe. (broadcast/send have
        # no such barrier; they clean up differently below.)
        if seq >= 1:
            _kv().kv_del(b"collective", self._key(op, seq - 1, self.rank))
        return out

    # -- collectives ----------------------------------------------------
    def allgather(self, value, timeout: float = 60.0) -> List[Any]:
        return self._gather_all("ag", value, timeout)

    def allreduce(self, arr: np.ndarray, op: str = "sum",
                  timeout: float = 60.0) -> np.ndarray:
        parts = self._gather_all("ar", np.asarray(arr), timeout)
        out = parts[0].copy()
        for p in parts[1:]:
            if op == "sum":
                out = out + p
            elif op == "max":
                out = np.maximum(out, p)
            elif op == "min":
                out = np.minimum(out, p)
            elif op == "prod":
                out = out * p
            else:
                raise ValueError(f"unknown reduce op {op!r}")
        return out

    def reduce(self, arr, *, dst_rank: int = 0, op: str = "sum",
               timeout: float = 60.0) -> Optional[np.ndarray]:
        out = self.allreduce(arr, op=op, timeout=timeout)
        return out if self.rank == dst_rank else None

    def broadcast(self, arr, *, src_rank: int = 0,
                  timeout: float = 60.0) -> np.ndarray:
        # The source never waits for receivers, so it may NOT delete old
        # payloads on a fixed lag — a burst of broadcasts would outrun a
        # slow receiver and strand it polling a deleted key. Receivers ack
        # each read; the source reclaims a payload only once every peer's
        # ack for it is present.
        seq = self._next_seq("bc")
        if self.rank == src_rank:
            self._put("bc", seq, src_rank, np.asarray(arr))
            self._gc_acked("bc", seq)
            return np.asarray(arr)
        value = self._get("bc", seq, src_rank, timeout)
        self._put("bc_ack", seq, self.rank, True)
        return value

    def _gc_acked(self, op: str, cur_seq: int) -> None:
        """Source-side cleanup: delete payloads whose acks are complete.

        A watermark (lowest un-collected seq) advances monotonically, so
        every seq is eventually revisited — no leak behind a laggard —
        and the common case (all caught up) costs world_size kv_gets for
        exactly one seq, not a window scan.
        """
        kv = _kv()
        mark = self._gc_marks.get(op, 0)
        while mark < cur_seq:
            acked = all(
                kv.kv_get(b"collective", self._key(f"{op}_ack", mark, r))
                is not None
                for r in range(self.world_size) if r != self.rank)
            if not acked:
                break  # retry from here on the next broadcast
            kv.kv_del(b"collective", self._key(op, mark, self.rank))
            for r in range(self.world_size):
                kv.kv_del(b"collective", self._key(f"{op}_ack", mark, r))
            mark += 1
        self._gc_marks[op] = mark

    def reducescatter(self, arr, op: str = "sum",
                      timeout: float = 60.0) -> np.ndarray:
        full = self.allreduce(arr, op=op, timeout=timeout)
        return np.array_split(full, self.world_size)[self.rank]

    def barrier(self, timeout: float = 60.0) -> None:
        self._gather_all("bar", 0, timeout)

    def send(self, arr, dst_rank: int, timeout: float = 60.0) -> None:
        op = f"p2p{self.rank}to{dst_rank}"
        self._put(op, self._next_seq(op), self.rank, np.asarray(arr))

    def recv(self, src_rank: int, timeout: float = 60.0) -> np.ndarray:
        # Single consumer: the receiver deletes the key it just read.
        op = f"p2p{src_rank}to{self.rank}"
        seq = self._next_seq(op)
        value = self._get(op, seq, src_rank, timeout)
        _kv().kv_del(b"collective", self._key(op, seq, src_rank))
        return value


def init_collective_group(world_size: int, rank: int,
                          backend: str = "kv",
                          group_name: str = "default",
                          incarnation: int = 0) -> CollectiveGroup:
    """ref: collective.py:120 — backend is always the KV transport here
    (NCCL's role is taken by in-graph XLA collectives).  Restarted gangs
    must pass a bumped `incarnation` (all ranks agree on it, e.g. the
    trainer's attempt counter) or a fresh group_name."""
    with _lock:
        g = CollectiveGroup(group_name, world_size, rank,
                            incarnation=incarnation)
        _groups[group_name] = g
    return g


def get_group(group_name: str = "default") -> CollectiveGroup:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(f"collective group {group_name!r} not initialized")
    return g


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        g = _groups.pop(group_name, None)
    if g is not None:  # best-effort KV cleanup of this group's keys
        try:
            w = _kv()
            prefix = f"coll/{group_name}/i{g.incarnation}/".encode()
            for k in w.kv_keys(b"collective", prefix):
                w.kv_del(b"collective", k)
        except Exception:  # noqa: BLE001
            pass


# module-level convenience (mirrors ray.util.collective free functions)
def allreduce(arr, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(arr, op=op)


def allgather(value, group_name: str = "default"):
    return get_group(group_name).allgather(value)


def broadcast(arr, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(arr, src_rank=src_rank)


def reducescatter(arr, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(arr, op=op)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def send(arr, dst_rank: int, group_name: str = "default"):
    get_group(group_name).send(arr, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return get_group(group_name).recv(src_rank)
