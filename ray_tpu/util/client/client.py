"""Thin client worker: the driver API forwarded to a ClientProxyServer.

Analogue of the reference client-side worker (ref: util/client/worker.py
— Worker class proxying ray.* over gRPC). Implements the same duck type
as DistributedCoreWorker/LocalCoreWorker, so `ray_tpu.remote/get/put/...`
work unchanged; every method is one `invoke` RPC. ObjectRefs and
ActorHandles travel by value (their ids), ownership stays with the proxy
server's driver.
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional

import cloudpickle


class _GcsShim:
    """worker.gcs.call(...) forwarded through the proxy (library
    internals — collectives, autoscaler sdk — use it directly)."""

    def __init__(self, client: "ClientWorker"):
        self._client = client

    def call(self, service: str, method: str,
             timeout: Optional[float] = None, **kwargs) -> Any:
        kwargs["timeout"] = timeout
        blob = self._client._rpc.call(
            "RayClient", "relay_gcs", svc=service, meth=method,
            kwargs_blob=cloudpickle.dumps(kwargs),
            timeout=None if timeout is None else timeout + 10)
        return pickle.loads(blob)


class ClientWorker:
    """Connected via ray_tpu.init(address="ray-tpu://host:port")."""

    def __init__(self, address: str):
        from ray_tpu.core.distributed.rpc import EventLoopThread, SyncRpcClient

        assert address.startswith("ray-tpu://")
        self.proxy_address = address[len("ray-tpu://"):]
        self.loop_thread = EventLoopThread("client")
        self._rpc = SyncRpcClient(self.proxy_address, self.loop_thread)
        info = self._invoke_raw("server_info")
        self.job_id = info["job_id"]
        self.gcs_address = info["gcs_address"]
        self.node_id = info["node_id"]
        self.address = f"client://{self.proxy_address}"
        self.gcs = _GcsShim(self)

    def _invoke_raw(self, method: str) -> dict:
        return self._rpc.call("RayClient", method, timeout=30)

    def _invoke(self, method: str, *args,
                _timeout: Optional[float] = 300.0, **kwargs) -> Any:
        blob = self._rpc.call(
            "RayClient", "invoke", target=method,
            args_blob=cloudpickle.dumps((args, kwargs)),
            timeout=_timeout)
        return pickle.loads(blob)

    # -- driver API (duck type of DistributedCoreWorker) ----------------
    @staticmethod
    def _reject_streaming(options) -> None:
        # An ObjectRefGenerator holds the server driver's live runtime
        # (locks, sockets) and cannot cross the proxy; fail BEFORE
        # submission, not with a pickling error after side effects ran.
        if getattr(options, "num_returns", 1) == "streaming":
            raise NotImplementedError(
                "num_returns='streaming' is not supported through the "
                "ray-tpu:// client proxy (run the driver in-cluster)")

    def submit_task(self, func, args, kwargs, options):
        self._reject_streaming(options)
        return self._invoke("submit_task", func, args, kwargs, options)

    def submit_streaming_task(self, func, args, kwargs, options):
        self._reject_streaming(options)

    def submit_actor_task(self, actor_id, method_name, args, kwargs,
                          options):
        self._reject_streaming(options)
        return self._invoke("submit_actor_task", actor_id, method_name,
                            args, kwargs, options)

    def create_actor(self, cls, args, kwargs, options):
        return self._invoke("create_actor", cls, args, kwargs, options)

    def get(self, refs, timeout=None):
        return self._invoke("get", refs, timeout,
                            _timeout=None if timeout is None
                            else timeout + 30)

    def put(self, value):
        return self._invoke("put", value)

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        return self._invoke("wait", refs, num_returns, timeout,
                            fetch_local,
                            _timeout=None if timeout is None
                            else timeout + 30)

    def get_actor(self, name, namespace=None):
        return self._invoke("get_actor", name, namespace)

    def kill_actor(self, actor_id, no_restart=True):
        return self._invoke("kill_actor", actor_id, no_restart)

    def cancel(self, ref, force=False, recursive=True):
        return self._invoke("cancel", ref, force, recursive)

    def actor_state(self, actor_id):
        return self._invoke("actor_state", actor_id)

    def create_placement_group(self, pg_id, bundles, strategy,
                               name=None, detached=False):
        return self._invoke("create_placement_group", pg_id, bundles,
                            strategy, name=name, detached=detached)

    def get_placement_group(self, pg_id):
        return self._invoke("get_placement_group", pg_id)

    def remove_placement_group(self, pg_id):
        return self._invoke("remove_placement_group", pg_id)

    def list_placement_groups(self):
        return self._invoke("list_placement_groups")

    def kv_put(self, namespace, key, value, overwrite=True):
        return self._invoke("kv_put", namespace, key, value, overwrite)

    def kv_get(self, namespace, key):
        return self._invoke("kv_get", namespace, key)

    def kv_del(self, namespace, key):
        return self._invoke("kv_del", namespace, key)

    def kv_keys(self, namespace, prefix=b""):
        return self._invoke("kv_keys", namespace, prefix)

    def cluster_resources(self) -> Dict[str, float]:
        return self._invoke("cluster_resources")

    def available_resources(self) -> Dict[str, float]:
        return self._invoke("available_resources")

    def nodes(self) -> List[dict]:
        return self._invoke("nodes")

    def shutdown(self) -> None:
        """Disconnect the client; the proxy's driver (and everything it
        owns) stays up for other clients."""
        try:
            self._rpc.close()
        finally:
            self.loop_thread.stop()
