"""Ray-Client equivalent: drive a remote cluster from a thin client.

Analogue of the reference Ray Client (ref: python/ray/util/client/ —
ARCHITECTURE.md: "the server runs ray.init() and proxies"; server/
server.py:96 RayletServicer). The proxy server process holds ONE real
driver connection to the cluster; thin clients forward every driver-API
call to it over the same RPC framing the rest of the stack uses.
`ray_tpu.init(address="ray-tpu://host:port")` selects this mode.
"""
from ray_tpu.util.client.client import ClientWorker  # noqa: F401
from ray_tpu.util.client.server import ClientProxyServer  # noqa: F401
