"""Client proxy server: hosts a real driver, serves thin clients.

Analogue of the reference client server (ref: util/client/server/
server.py:96 — a gRPC servicer that executes driver-side operations on
behalf of remote clients). One service, two methods:

    invoke(method, args_blob)          -> run a DistributedCoreWorker
                                          method, return pickled result
    relay_gcs(service, method, blob)   -> forward a raw GCS RPC (library
                                          internals use worker.gcs.call)

The server process IS the driver: objects put by clients are owned here,
so they outlive any individual client connection (the reference's client
server owns references the same way).
"""
from __future__ import annotations

import asyncio
import logging
import pickle
from typing import Optional

import cloudpickle

logger = logging.getLogger(__name__)

# Driver-API methods clients may proxy. An allowlist, not getattr on
# anything the wire names: the payloads are pickles (trusted cluster
# perimeter, same as the reference client), but method dispatch should
# still be a closed set.
ALLOWED = frozenset({
    "submit_task", "submit_actor_task", "create_actor", "get", "put",
    "wait", "get_actor", "kill_actor", "cancel", "actor_state",
    "create_placement_group", "get_placement_group",
    "remove_placement_group", "list_placement_groups",
    "kv_put", "kv_get", "kv_del", "kv_keys",
    "cluster_resources", "available_resources", "nodes",
})


class _ClientService:
    def __init__(self, worker):
        self._worker = worker
        loop = asyncio.get_event_loop()
        self._loop = loop

    async def invoke(self, target: str, args_blob: bytes) -> bytes:
        if target not in ALLOWED:
            raise ValueError(f"client may not invoke {target!r}")
        args, kwargs = pickle.loads(args_blob)
        fn = getattr(self._worker, target)
        # Worker methods block (get/wait): keep the proxy loop free.
        result = await asyncio.get_running_loop().run_in_executor(
            None, lambda: fn(*args, **kwargs))
        return cloudpickle.dumps(result)

    async def relay_gcs(self, svc: str, meth: str,
                        kwargs_blob: bytes) -> bytes:
        kwargs = pickle.loads(kwargs_blob)
        timeout = kwargs.pop("timeout", 30)
        result = await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._worker.gcs.call(svc, meth,
                                                timeout=timeout, **kwargs))
        return cloudpickle.dumps(result)

    def server_info(self) -> dict:
        return {
            "job_id": self._worker.job_id,
            "gcs_address": self._worker.gcs_address,
            "node_id": self._worker.node_id,
        }


class ClientProxyServer:
    def __init__(self, gcs_address: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 10001):
        import ray_tpu
        from ray_tpu.api import _global_worker

        ray_tpu.init(address=gcs_address, ignore_reinit_error=True)
        self._worker = _global_worker()
        self.host = host
        self.port = port
        self._server = None

    async def start(self) -> int:
        from ray_tpu.core.distributed.rpc import RpcServer

        self._server = RpcServer(self.host, self.port)
        self._server.add_service("RayClient", _ClientService(self._worker))
        self.port = await self._server.start()
        logger.info("client proxy on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop()


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--address", default=None,
                        help="GCS address (default: start a local cluster)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=10001)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="[client-proxy] %(message)s")

    async def run():
        srv = ClientProxyServer(args.address, args.host, args.port)
        port = await srv.start()
        print(f"CLIENT_PROXY_PORT={port}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
