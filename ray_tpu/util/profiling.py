"""On-demand CPU profiling + signal-safe diagnosis of live workers.

Two complementary capture paths, mirroring the reference dashboard's
profiling stack (ref: dashboard/modules/reporter/profile_manager.py:75
CpuProfilingManager — attaches py-spy to a worker PID on demand):

  * Sampling (`sample_stacks`/`profile_here`, the `profile` worker RPC):
    a sampler thread inside the worker walks sys._current_frames() —
    cheap, produces collapsed flamegraph lines, but needs the GIL, so a
    thread stuck in native code holding the GIL is invisible to it.
  * Signal-safe dumps (`register_stack_dump_handler` + SIGUSR1, the
    `ray-tpu stack` path): faulthandler's C-level handler writes every
    thread's traceback WITHOUT taking the GIL — the `ray stack`
    equivalent that still works when the process is wedged in a
    GIL-holding native call. The daemon signals, tails the per-pid dump
    file, and `parse_faulthandler_dump`/`summarize_stacks` turn the text
    into grouped cluster-wide answers ("412/512 workers blocked in
    all_reduce at collective.py:...").

Per-task resource attribution (`TaskUsageProbe`) lives here too: thread
CPU-time, RSS delta + peak, and opt-in JAX device-memory stats wrapped
around each task attempt by the executor.
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import Counter
from typing import Dict, List, Optional

# Serializes tracemalloc windows: tracing state is process-global, so
# overlapping heap-profile requests must queue, not stop each other.
HEAP_TRACE_LOCK = threading.Lock()

# Threads currently sampling (module-global, GIL-guarded): a sampler
# must never appear in ANOTHER concurrent sampler's output — its busy
# loop would masquerade as application load.
_SAMPLER_TIDS: set = set()


def sample_stacks(duration_s: float = 2.0, interval_s: float = 0.01,
                  exclude_thread: Optional[int] = None) -> Dict[str, int]:
    """Sample all threads' stacks for `duration_s`; returns collapsed
    stack -> count (root;...;leaf, frames as module:function:line)."""
    counts: Counter = Counter()
    deadline = time.monotonic() + duration_s
    me = threading.get_ident()
    _SAMPLER_TIDS.add(me)
    try:
        import sys

        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if (tid == me or tid == exclude_thread
                        or tid in _SAMPLER_TIDS):
                    continue
                parts: List[str] = []
                f = frame
                while f is not None:
                    code = f.f_code
                    parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                                 f"{code.co_name}:{f.f_lineno}")
                    f = f.f_back
                counts[";".join(reversed(parts))] += 1
            time.sleep(interval_s)
    finally:
        _SAMPLER_TIDS.discard(me)
    return dict(counts)


def profile_here(duration_s: float = 2.0,
                 interval_s: float = 0.01) -> dict:
    """Sample from the CALLING thread (which excludes itself): no helper
    thread, or its join() would show up at ~100% of samples. A capture
    too short to take any sample (duration < interval on a loaded box)
    returns an honest empty report — samples=0, not a fabricated 1."""
    stacks = sample_stacks(duration_s, interval_s)
    total = sum(stacks.values())
    leaves: Counter = Counter()
    for stack, n in stacks.items():
        leaves[stack.rsplit(";", 1)[-1]] += n
    return {
        "samples": total,
        "stacks": stacks,                       # collapsed flamegraph
        "top": leaves.most_common(20),
        "duration_s": duration_s,
    }


def merge_reports(reports: List[dict]) -> dict:
    """Merge several `profile_here` reports (one per worker) into one
    cluster-wide report: identical code paths aggregate, so a hot frame
    on 50 workers shows up once at 50x weight."""
    stacks: Counter = Counter()
    total = 0
    dur = 0.0
    for r in reports:
        for s, n in (r.get("stacks") or {}).items():
            stacks[s] += n
        total += int(r.get("samples", 0))
        dur = max(dur, float(r.get("duration_s", 0.0)))
    leaves: Counter = Counter()
    for s, n in stacks.items():
        leaves[s.rsplit(";", 1)[-1]] += n
    return {"samples": total, "stacks": dict(stacks),
            "top": leaves.most_common(20), "duration_s": dur,
            "workers": len(reports)}


def render_report(report: dict) -> str:
    samples = int(report.get("samples", 0))
    header = f"{samples} samples over {report['duration_s']:.1f}s"
    if "workers" in report:
        header += f" across {report['workers']} workers"
    if not samples:
        return header + " (capture shorter than the sampling interval?)"
    lines = [header, "top frames (leaf, % of samples):"]
    for frame, n in report["top"]:
        lines.append(f"  {100.0 * n / samples:5.1f}%  {frame}")
    return "\n".join(lines)


def write_flamegraph_collapsed(report: dict, path: str) -> str:
    """Collapsed-stack file for flamegraph.pl / speedscope import."""
    with open(path, "w") as f:
        for stack, n in sorted(report["stacks"].items()):
            f.write(f"{stack} {n}\n")
    return path


# ---------------------------------------------------------------------------
# Signal-safe stack dumps (ref: `ray stack` / faulthandler). The worker
# registers at boot; the daemon owns signal + tail + parse.
# ---------------------------------------------------------------------------

# Files handed to faulthandler.register must stay open for the process's
# lifetime; rooted here so GC can never close them under the C handler.
_DUMP_FILES: List = []


def node_log_dir(node_id: str) -> str:
    """The node's log dir, computed identically by the daemon and its
    workers (env override or a node-id-derived default), so the dump
    file rendezvous needs no extra plumbing through the spawn paths."""
    import tempfile

    from ray_tpu.core.config import get_config

    return get_config().log_dir or os.path.join(
        tempfile.gettempdir(), "ray_tpu_logs", node_id[:12])


def stack_dump_path(log_dir: str, pid: int) -> str:
    return os.path.join(log_dir, f"stack-{pid}.dump")


def register_stack_dump_handler(dump_path: str) -> bool:
    """Register faulthandler on SIGUSR1 writing all-thread tracebacks to
    `dump_path` (append mode — O_APPEND keeps concurrent truncate-based
    rotation safe). faulthandler's handler runs at the C level and walks
    thread states WITHOUT the GIL, so this works even when a thread is
    wedged in GIL-holding native code — the exact case the in-process
    sampling RPC can never see."""
    import faulthandler
    import signal

    if not hasattr(faulthandler, "register"):  # Windows
        return False
    os.makedirs(os.path.dirname(dump_path) or ".", exist_ok=True)
    f = open(dump_path, "a")
    faulthandler.register(signal.SIGUSR1, file=f, all_threads=True,
                          chain=False)
    _DUMP_FILES.append(f)
    return True


_THREAD_RE = re.compile(r"^(Current thread|Thread) (0x[0-9a-fA-F]+)")
_FRAME_RE = re.compile(r'^  File "([^"]+)", line (\d+) in (.*)$')


def parse_faulthandler_dump(text: str) -> List[dict]:
    """Parse one faulthandler dump into per-thread frame lists (frames
    most-recent-first, as printed): [{"thread", "current", "frames":
    ["file.py:func:line", ...]}, ...]."""
    threads: List[dict] = []
    cur: Optional[dict] = None
    for line in text.splitlines():
        m = _THREAD_RE.match(line)
        if m:
            cur = {"thread": m.group(2),
                   "current": m.group(1).startswith("Current"),
                   "frames": []}
            threads.append(cur)
            continue
        m = _FRAME_RE.match(line)
        if m and cur is not None:
            path, lineno, func = m.groups()
            cur["frames"].append(
                f"{path.rsplit('/', 1)[-1]}:{func}:{lineno}")
    return threads


def summarize_stacks(node_results: List[dict]) -> List[dict]:
    """Group identical thread stacks across every worker of a cluster
    dump (`Diagnosis.dump_stacks` output): the one-line answer to "where
    is everyone?" — e.g. 412/512 workers sharing the exact all_reduce
    frame. Sorted most-common first."""
    groups: Dict[tuple, set] = {}
    total: set = set()
    for nres in node_results or ():
        for w in nres.get("workers", ()):
            wid = (nres.get("node_id"), w.get("pid"))
            if w.get("ok"):
                total.add(wid)
            for t in w.get("threads", ()):
                frames = tuple(t.get("frames") or ())
                if not frames:
                    continue
                groups.setdefault(frames, set()).add(wid)
    out = [{"workers": len(v), "total": len(total),
            "leaf": k[0], "frames": list(k)}
           for k, v in groups.items()]
    out.sort(key=lambda g: (-g["workers"], g["leaf"]))
    return out


# ---------------------------------------------------------------------------
# Per-task resource attribution (executor-side; rides the task-event
# record of each attempt — ISSUE 5 tentpole part 2).
# ---------------------------------------------------------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_tls = threading.local()
_JAX_DEVICES = None


def _rss_bytes() -> Optional[int]:
    """Current process RSS via a per-thread cached /proc/self/statm fd
    (seek+read, no open per task — the probe runs on every attempt and
    must stay in the single-digit-microsecond range)."""
    f = getattr(_tls, "statm", None)
    if f is None:
        try:
            f = _tls.statm = open("/proc/self/statm", "rb", buffering=0)
        except OSError:
            _tls.statm = False
            return None
    if f is False:
        return None
    try:
        f.seek(0)
        return int(f.read(80).split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return None


def _peak_rss_bytes() -> Optional[int]:
    """Process high-water RSS (ru_maxrss — one cheap syscall; Linux
    reports KiB)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001
        return None


_DEVICE_MEM_ENABLED: Optional[bool] = None


def _device_bytes() -> Optional[int]:
    """Summed bytes_in_use across local JAX devices — opt-in
    (RAY_TPU_TASK_EVENTS_DEVICE_MEM): memory_stats() is a device
    runtime call, not something to pay per noop task. The flag is
    resolved once per process (workers get it through their spawn
    env), keeping the disabled path to one global read per probe."""
    global _DEVICE_MEM_ENABLED
    if _DEVICE_MEM_ENABLED is None:
        from ray_tpu.core.config import get_config

        _DEVICE_MEM_ENABLED = bool(get_config().task_events_device_mem)
    if not _DEVICE_MEM_ENABLED:
        return None
    global _JAX_DEVICES
    if _JAX_DEVICES is None:
        try:
            import jax

            _JAX_DEVICES = list(jax.local_devices())
        except Exception:  # noqa: BLE001 — no jax runtime here
            _JAX_DEVICES = []
    total = 0
    seen = False
    for d in _JAX_DEVICES:
        try:
            st = d.memory_stats()
        except Exception:  # noqa: BLE001 backend without stats
            continue
        if st:
            total += int(st.get("bytes_in_use", 0))
            seen = True
    return total if seen else None


# Process-wide RSS snapshot refreshed at most every TTL: probe starts
# read the CACHED value (a dict lookup, no syscall) — the statm read
# happens once per TTL window across all executor threads.
_RSS_CACHE_TTL_S = 0.1
_RSS_CACHE = [0.0, None]  # [monotonic ts, rss bytes]


def _cached_rss() -> Optional[int]:
    now = time.monotonic()
    if _RSS_CACHE[1] is None or now - _RSS_CACHE[0] > _RSS_CACHE_TTL_S:
        _RSS_CACHE[1] = _rss_bytes()
        _RSS_CACHE[0] = now
    return _RSS_CACHE[1]


class TaskUsageProbe:
    """Start/finish pair wrapped around one task attempt by the
    executor: thread CPU-time (time.thread_time — this thread only, so
    concurrent attempts don't bleed into each other), RSS delta + peak,
    and opt-in device memory. finish() returns the fields that ride the
    attempt's task-event record.

    Cost discipline: micro tasks get CPU-time only — thread_time is a
    GIL-holding vdso-cheap read, while the statm/getrusage reads each
    release the GIL around a syscall, and on a contended host those
    releases amplify into thread switches (measured: ~25% of many_tasks
    noop throughput when probed per attempt). Memory detail is taken
    only for attempts that ran >= MIN_DETAIL_WALL_S, where it is both
    amortized and actually meaningful (a noop's RSS delta is allocator
    noise); the start baseline comes from a 100ms-TTL cached process
    RSS, accurate at the MB scales attribution answers for."""

    MIN_DETAIL_WALL_S = 0.01

    __slots__ = ("t0", "cpu0", "rss0", "dev0")

    def __init__(self):
        self.t0 = time.monotonic()
        self.cpu0 = time.thread_time()
        self.rss0 = _cached_rss()
        self.dev0 = _device_bytes()

    def finish(self) -> dict:
        out = {"cpu_time_s": round(time.thread_time() - self.cpu0, 6)}
        if time.monotonic() - self.t0 >= self.MIN_DETAIL_WALL_S:
            rss = _rss_bytes()
            if rss is not None:
                _RSS_CACHE[1] = rss
                _RSS_CACHE[0] = time.monotonic()
                if self.rss0 is not None:
                    out["rss_delta_bytes"] = rss - self.rss0
            peak = _peak_rss_bytes()
            if peak is not None:
                out["rss_peak_bytes"] = peak
        dev = _device_bytes()
        if dev is not None:
            out["device_mem_bytes"] = dev
            if self.dev0 is not None:
                out["device_mem_delta_bytes"] = dev - self.dev0
        return out
