"""On-demand CPU profiling of live workers: a py-spy-lite.

Analogue of the reference's dashboard profiling
(ref: dashboard/modules/reporter/profile_manager.py:75
CpuProfilingManager — attaches py-spy to a worker PID on demand). py-spy
isn't in this image, so the equivalent samples the target process's own
thread stacks via sys._current_frames() from a sampler thread inside the
worker (workers expose it as the `profile` RPC). Output: collapsed
flamegraph lines ("a;b;c count") and a top-of-stacks summary — the same
artifacts a py-spy `record --format raw` run produces.
"""
from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict, List, Optional

# Serializes tracemalloc windows: tracing state is process-global, so
# overlapping heap-profile requests must queue, not stop each other.
HEAP_TRACE_LOCK = threading.Lock()


def sample_stacks(duration_s: float = 2.0, interval_s: float = 0.01,
                  exclude_thread: Optional[int] = None) -> Dict[str, int]:
    """Sample all threads' stacks for `duration_s`; returns collapsed
    stack -> count (root;...;leaf, frames as module:function:line)."""
    counts: Counter = Counter()
    deadline = time.monotonic() + duration_s
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me or tid == exclude_thread:
                continue
            parts: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{code.co_name}:{f.f_lineno}")
                f = f.f_back
            counts[";".join(reversed(parts))] += 1
        time.sleep(interval_s)
    return dict(counts)


def profile_here(duration_s: float = 2.0,
                 interval_s: float = 0.01) -> dict:
    """Sample from the CALLING thread (which excludes itself): no helper
    thread, or its join() would show up at ~100% of samples."""
    stacks = sample_stacks(duration_s, interval_s)
    total = sum(stacks.values()) or 1
    leaves: Counter = Counter()
    for stack, n in stacks.items():
        leaves[stack.rsplit(";", 1)[-1]] += n
    return {
        "samples": total,
        "stacks": stacks,                       # collapsed flamegraph
        "top": leaves.most_common(20),
        "duration_s": duration_s,
    }


def render_report(report: dict) -> str:
    lines = [f"{report['samples']} samples over "
             f"{report['duration_s']:.1f}s"]
    lines.append("top frames (leaf, % of samples):")
    for frame, n in report["top"]:
        lines.append(f"  {100.0 * n / report['samples']:5.1f}%  {frame}")
    return "\n".join(lines)


def write_flamegraph_collapsed(report: dict, path: str) -> str:
    """Collapsed-stack file for flamegraph.pl / speedscope import."""
    with open(path, "w") as f:
        for stack, n in sorted(report["stacks"].items()):
            f.write(f"{stack} {n}\n")
    return path
