"""Cross-language workers: call C++-DEFINED remote functions from Python.

Counterpart of the C++ worker API (cpp/include/ray_tpu_worker/
ray_tpu_worker.hpp; ref: the reference's C++ worker runtime,
cpp/src/ray/runtime/task/task_executor.cc, and Python-side cross-language
calls, python/ray/cross_language.py). A compiled C++ worker binary
registers functions with RAY_TPU_REMOTE and serves them over the native
frame protocol; `CppWorker` spawns it (handshake: `CPP_WORKER_PORT=` on
stdout), and `.invoke()/.submit()` route calls with the shared Value
data model (None/bool/int/float/bytes/str/list/dict).

    worker = CppWorker("./my_cpp_worker")
    worker.invoke("Add", 2.0, 3.0)          # -> 5.0, blocking
    fut = worker.submit("Add", 1, 2)        # concurrent.futures.Future
    worker.functions()                      # registered names
    worker.close()
"""
from __future__ import annotations

import os
import subprocess
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional

from ray_tpu.core.distributed.rpc import EventLoopThread, SyncRpcClient


class CppFunctionError(Exception):
    """A C++ remote function raised / was not found."""


class CppWorker:
    """Owns one C++ worker process and a connection pool to it."""

    def __init__(self, binary: str, *, args: Optional[List[str]] = None,
                 startup_timeout_s: float = 30.0, max_concurrency: int = 8):
        if not os.path.exists(binary):
            raise FileNotFoundError(f"C++ worker binary {binary!r}")
        from ray_tpu.core.distributed.driver import (
            pdeathsig_preexec,
            _read_handshake,
        )

        self._proc = subprocess.Popen(
            [binary, *(args or [])], stdout=subprocess.PIPE, stderr=None,
            preexec_fn=pdeathsig_preexec)
        info = _read_handshake(self._proc, r"CPP_WORKER_PORT=(?P<port>\d+)",
                               "C++ worker")
        self.address = f"127.0.0.1:{info['port']}"
        self._loop = EventLoopThread("cpp-worker")
        self._client = SyncRpcClient(self.address, self._loop)
        self._pool = ThreadPoolExecutor(
            max_workers=max_concurrency,
            thread_name_prefix="cpp-worker-call")
        self._closed = False
        self._lock = threading.Lock()

    # -- calls ----------------------------------------------------------
    def invoke(self, fn: str, *args: Any, timeout: float = 60.0) -> Any:
        """Call a registered C++ function; blocks for the result."""
        reply = self._client.call("CppWorker", "invoke", timeout=timeout,
                                  fn=fn, args=list(args))
        if not reply.get("ok"):
            raise CppFunctionError(reply.get("error", "unknown error"))
        return reply.get("value")

    def submit(self, fn: str, *args: Any,
               timeout: float = 60.0) -> "Future":
        """Async call; returns a concurrent.futures.Future."""
        return self._pool.submit(self.invoke, fn, *args, timeout=timeout)

    def functions(self, timeout: float = 10.0) -> List[str]:
        reply = self._client.call("CppWorker", "list_functions",
                                  timeout=timeout)
        if not reply.get("ok"):
            raise CppFunctionError(reply.get("error", ""))
        return sorted(reply.get("value") or [])

    def ping(self, timeout: float = 10.0) -> bool:
        reply = self._client.call("CppWorker", "ping", timeout=timeout)
        return reply.get("value") == "pong"

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._pool.shutdown(wait=False)
        self._client.close()
        self._loop.stop()
        try:
            self._proc.terminate()
            self._proc.wait(timeout=5)
        except Exception:  # noqa: BLE001
            try:
                self._proc.kill()
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self) -> "CppWorker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
