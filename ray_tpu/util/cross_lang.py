"""Cross-language workers: call C++-DEFINED remote functions from Python.

Counterpart of the C++ worker API (cpp/include/ray_tpu_worker/
ray_tpu_worker.hpp; ref: the reference's C++ worker runtime,
cpp/src/ray/runtime/task/task_executor.cc, and Python-side cross-language
calls, python/ray/cross_language.py). A compiled C++ worker binary
registers functions with RAY_TPU_REMOTE and serves them over the native
frame protocol; `CppWorker` spawns it (handshake: `CPP_WORKER_PORT=` on
stdout), and `.invoke()/.submit()` route calls with the shared Value
data model (None/bool/int/float/bytes/str/list/dict).

    worker = CppWorker("./my_cpp_worker")
    worker.invoke("Add", 2.0, 3.0)          # -> 5.0, blocking
    fut = worker.submit("Add", 1, 2)        # concurrent.futures.Future
    worker.functions()                      # registered names

C++ ACTORS (stateful; ref: cpp/include/ray/api/actor_handle.h —
ActorHandle<T>.Task(&T::Method) with serial per-actor execution):

    h = worker.create_actor("Counter", 10)
    h.call("Inc", 5)                        # -> 15, blocking
    fut = h.submit("Inc", 1)                # ordered: per-handle FIFO
    h.kill()                                # state destroyed
    worker.close()
"""
from __future__ import annotations

import os
import subprocess
import threading
import weakref
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional

from ray_tpu.core.distributed.rpc import EventLoopThread, SyncRpcClient
from ray_tpu.core.distributed.wire import CODEC_TYPED


class CppFunctionError(Exception):
    """A C++ remote function raised / was not found."""


def _unwrap(reply: dict) -> Any:
    """Unpack the app-level {'ok', 'value'|'error'} envelope."""
    if not reply.get("ok"):
        raise CppFunctionError(reply.get("error", "unknown error"))
    return reply.get("value")


def _reap_actor(worker_ref, actor_id: int, serial) -> None:
    """GC finalizer for a dropped handle: C++ actors die with their
    last handle, like Python actors (must not reference the handle)."""
    serial.shutdown(wait=False)
    w = worker_ref()
    if w is None or w._closed:
        return
    try:
        w._client.call("CppWorker", "kill_actor", timeout=5,
                       actor_id=actor_id)
    except Exception:  # noqa: BLE001 worker already gone
        pass


class CppActorHandle:
    """Handle to a stateful actor living in the C++ worker process.

    Method calls execute SERIALLY on the instance (C++ side holds a
    per-instance mutex) and `submit()` preserves per-handle submission
    order with a single dispatch thread — the same ordering contract
    Python actor handles give their callers. A method that raises keeps
    the actor alive (matching Python actors: task errors are not actor
    deaths); `kill()` destroys the instance, after which every call
    fails with a clear "no such C++ actor" error.
    """

    def __init__(self, worker: "CppWorker", actor_id: int,
                 type_name: str):
        self._worker = worker
        self._id = actor_id
        self._type = type_name
        self._serial = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"cpp-actor-{actor_id}")
        self._finalizer = weakref.finalize(
            self, _reap_actor, weakref.ref(worker), actor_id,
            self._serial)

    @property
    def actor_id(self) -> int:
        return self._id

    def _call_rpc(self, method: str, args: tuple,
                  timeout: float) -> Any:
        return _unwrap(self._worker._client.call(
            "CppWorker", "call_actor", timeout=timeout,
            actor_id=self._id, name=method, args=list(args)))

    def call(self, method: str, *args: Any,
             timeout: float = 60.0) -> Any:
        """Invoke an actor method; blocks for the result. Rides the
        same serial dispatch thread as submit(), so a blocking call
        always observes every earlier submission from this handle."""
        return self.submit(method, *args, timeout=timeout).result()

    def submit(self, method: str, *args: Any,
               timeout: float = 60.0) -> "Future":
        """Async call; per-handle FIFO ordering is guaranteed."""
        return self._serial.submit(self._call_rpc, method, args,
                                   timeout)

    def kill(self, timeout: float = 30.0) -> None:
        """Destroy the actor instance (idempotence is an error: a
        second kill raises, mirroring ray.kill on a dead actor)."""
        self._serial.shutdown(wait=True)
        self._serial = ThreadPoolExecutor(   # handle stays usable for
            max_workers=1,                   # error-path calls
            thread_name_prefix=f"cpp-actor-{self._id}")
        self._finalizer.detach()             # kill is explicit now
        _unwrap(self._worker._client.call(
            "CppWorker", "kill_actor", timeout=timeout,
            actor_id=self._id))

    def __repr__(self) -> str:
        return f"CppActorHandle({self._type}#{self._id})"


class CppWorker:
    """Owns one C++ worker process and a connection pool to it."""

    def __init__(self, binary: str, *, args: Optional[List[str]] = None,
                 startup_timeout_s: float = 30.0, max_concurrency: int = 8):
        if not os.path.exists(binary):
            raise FileNotFoundError(f"C++ worker binary {binary!r}")
        from ray_tpu.core.distributed.driver import (
            pdeathsig_preexec,
            _read_handshake,
        )

        self._proc = subprocess.Popen(
            [binary, *(args or [])], stdout=subprocess.PIPE, stderr=None,
            preexec_fn=pdeathsig_preexec)
        info = _read_handshake(self._proc, r"CPP_WORKER_PORT=(?P<port>\d+)",
                               "C++ worker")
        self.address = f"127.0.0.1:{info['port']}"
        self._loop = EventLoopThread("cpp-worker")
        # The typed wire codec is the cross-language contract: C++
        # workers never see pickle (wire.py; ref: the reference's
        # proto3 cross-language seam).
        self._client = SyncRpcClient(self.address, self._loop,
                                     codec=CODEC_TYPED)
        self._pool = ThreadPoolExecutor(
            max_workers=max_concurrency,
            thread_name_prefix="cpp-worker-call")
        self._closed = False
        self._lock = threading.Lock()

    # -- calls ----------------------------------------------------------
    def invoke(self, fn: str, *args: Any, timeout: float = 60.0) -> Any:
        """Call a registered C++ function; blocks for the result."""
        return _unwrap(self._client.call("CppWorker", "invoke",
                                         timeout=timeout, fn=fn,
                                         args=list(args)))

    def submit(self, fn: str, *args: Any,
               timeout: float = 60.0) -> "Future":
        """Async call; returns a concurrent.futures.Future."""
        return self._pool.submit(self.invoke, fn, *args, timeout=timeout)

    def functions(self, timeout: float = 10.0) -> List[str]:
        return sorted(_unwrap(self._client.call(
            "CppWorker", "list_functions", timeout=timeout)) or [])

    def ping(self, timeout: float = 10.0) -> bool:
        reply = self._client.call("CppWorker", "ping", timeout=timeout)
        return reply.get("value") == "pong"

    # -- actors ---------------------------------------------------------
    def create_actor(self, type_name: str, *args: Any,
                     timeout: float = 60.0) -> CppActorHandle:
        """Construct a registered C++ actor; returns its handle."""
        actor_id = _unwrap(self._client.call(
            "CppWorker", "create_actor", timeout=timeout,
            type=type_name, args=list(args)))
        return CppActorHandle(self, int(actor_id), type_name)

    def actor_types(self, timeout: float = 10.0) -> List[str]:
        return sorted(_unwrap(self._client.call(
            "CppWorker", "list_actor_types", timeout=timeout)) or [])

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._pool.shutdown(wait=False)
        self._client.close()
        self._loop.stop()
        try:
            self._proc.terminate()
            self._proc.wait(timeout=5)
        except Exception:  # noqa: BLE001
            try:
                self._proc.kill()
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self) -> "CppWorker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
