"""Placement groups: gang resource reservation.

Analogue of the reference API (ref: python/ray/util/placement_group.py —
placement_group() :145, PlacementGroup handle :41; strategies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD). On TPU the headline use is
slice-atomic gangs: one bundle per host of a slice so a pjit program's hosts
are co-scheduled inside one ICI domain.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until reserved (or timeout); returns created-ness."""
        from ray_tpu.api import _global_worker

        worker = _global_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            info = worker.get_placement_group(self.id)
            if info is not None and info["state"] == "CREATED":
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: Optional[str] = None,
                    lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    from ray_tpu.api import _global_worker

    worker = _global_worker()
    pg_id = PlacementGroupID.generate()
    worker.create_placement_group(
        pg_id, [dict(b) for b in bundles], strategy, name=name,
        detached=(lifetime == "detached"))
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.api import _global_worker

    _global_worker().remove_placement_group(pg.id)


def placement_group_table() -> List[dict]:
    from ray_tpu.api import _global_worker

    return _global_worker().list_placement_groups()


def tpu_slice_placement_group(num_hosts: int, chips_per_host: int = 4,
                              cpus_per_host: float = 1.0) -> PlacementGroup:
    """A slice-atomic gang: one bundle per TPU host, STRICT_SPREAD across
    hosts (the TPU-native replacement for the reference's
    `TPU-{pod_type}-head` + per-host TPU resource pattern,
    ref: _private/accelerators/tpu.py:382)."""
    bundles = [{"CPU": cpus_per_host, "TPU": float(chips_per_host)}
               for _ in range(num_hosts)]
    return placement_group(bundles, strategy="STRICT_SPREAD")
