"""Placement groups: gang resource reservation.

Analogue of the reference API (ref: python/ray/util/placement_group.py —
placement_group() :145, PlacementGroup handle :41; strategies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD). On TPU the headline use is
slice-atomic gangs: one bundle per host of a slice so a pjit program's hosts
are co-scheduled inside one ICI domain.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until reserved (or timeout); returns created-ness.

        Long-polls the GCS (wait_pg, same pattern as actor resolution):
        the reply arrives on the gang's next state TRANSITION, so a
        pending gang costs one parked RPC per ~2s instead of a 50ms
        polling loop per waiting driver."""
        from ray_tpu.api import _global_worker

        worker = _global_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                info = worker.get_placement_group(self.id)
                return info is not None and info["state"] == "CREATED"
            park = 2.0 if remaining is None else min(2.0, remaining)
            info = worker.wait_placement_group(
                self.id, known_state="PENDING", park_s=park)
            if info is not None and info["state"] == "CREATED":
                return True
            if info is None or info["state"] == "REMOVED":
                return False

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: Optional[str] = None,
                    lifetime: Optional[str] = None,
                    bundle_labels: Optional[List[Optional[Dict[
                        str, str]]]] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    from ray_tpu.api import _global_worker

    worker = _global_worker()
    pg_id = PlacementGroupID.generate()
    worker.create_placement_group(
        pg_id, [dict(b) for b in bundles], strategy, name=name,
        detached=(lifetime == "detached"), bundle_labels=bundle_labels)
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.api import _global_worker

    _global_worker().remove_placement_group(pg.id)


def placement_group_table() -> List[dict]:
    from ray_tpu.api import _global_worker

    return _global_worker().list_placement_groups()


def ici_snake_order(num_hosts: int,
                    topology: Optional[str] = None) -> List[int]:
    """Bundle index -> TPU worker id, snaking through the host grid.

    A pjit program's collectives run fastest when consecutive ranks are
    ICI neighbours; a boustrophedon walk of the host grid keeps every
    adjacent pair one hop apart. `topology` is the host grid as "XxY"
    (e.g. "4x4"); None or a 1-D grid degrades to identity."""
    if not topology or "x" not in topology:
        return list(range(num_hosts))
    try:
        dims = [int(d) for d in topology.lower().split("x")]
    except ValueError:
        return list(range(num_hosts))
    cols = dims[0]
    rows = max(1, num_hosts // cols) if cols else 1
    order: List[int] = []
    for r in range(rows):
        row = list(range(r * cols, min((r + 1) * cols, num_hosts)))
        order.extend(reversed(row) if r % 2 else row)
    order.extend(range(len(order), num_hosts))  # ragged tail
    return order[:num_hosts]


def tpu_slice_placement_group(
        num_hosts: int, chips_per_host: int = 4,
        cpus_per_host: float = 1.0,
        topology: Optional[str] = None,
        bundle_order: Optional[Callable[[int, Optional[str]],
                                        List[int]]] = None
) -> PlacementGroup:
    """A slice-atomic gang: one bundle per TPU host, STRICT_SPREAD across
    hosts (the TPU-native replacement for the reference's
    `TPU-{pod_type}-head` + per-host TPU resource pattern,
    ref: _private/accelerators/tpu.py:382).

    `topology`/`bundle_order` pick an ICI-aware bundle ordering: bundle
    i carries a soft label preference for the TPU host whose worker id
    is order[i], so rank i of the gang lands on an ICI neighbour of
    rank i±1 (snake order by default; pass `bundle_order` for other
    wirings). The preference is soft — placement still succeeds on
    clusters without TPU_WORKER_ID labels."""
    order = (bundle_order(num_hosts, topology) if bundle_order is not None
             else ici_snake_order(num_hosts, topology))
    if sorted(order) != list(range(num_hosts)):
        raise ValueError(f"bundle_order must permute 0..{num_hosts - 1}, "
                         f"got {order}")
    bundles = [{"CPU": cpus_per_host, "TPU": float(chips_per_host)}
               for _ in range(num_hosts)]
    labels = [{"TPU_WORKER_ID": str(order[i])} for i in range(num_hosts)]
    return placement_group(bundles, strategy="STRICT_SPREAD",
                           bundle_labels=labels)
