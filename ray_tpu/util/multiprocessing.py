"""Drop-in multiprocessing.Pool over the cluster.

Analogue of the reference's Pool shim (ref: python/ray/util/
multiprocessing/pool.py — a Pool API whose workers are Ray actors, so
pools span machines). Each pool worker is one actor; apply/map calls
round-robin over them with the standard result types (ApplyResult /
chunked ordered map / imap / imap_unordered).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence


class TimeoutError(Exception):  # noqa: A001 — multiprocessing parity
    pass


class _PoolActorCls:
    """One pool worker; created lazily as a ray_tpu actor."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run(self, func, args, kwargs):
        return func(*args, **kwargs)

    def run_batch(self, func, chunk):
        return [func(*a) for a in chunk]


class ApplyResult:
    """multiprocessing.pool.ApplyResult parity over an ObjectRef."""

    def __init__(self, ref, callback=None, error_callback=None):
        self._ref = ref
        self._callback = callback
        self._error_callback = error_callback
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        threading.Thread(target=self._wait_thread, daemon=True).start()

    def _wait_thread(self):
        import ray_tpu

        try:
            self._value = ray_tpu.get(self._ref)
            if self._callback is not None:
                self._callback(self._value)
        except BaseException as e:  # noqa: BLE001
            self._error = e
            if self._error_callback is not None:
                self._error_callback(e)
        finally:
            self._done.set()

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result not ready")
        return self._error is None

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready within timeout")
        if self._error is not None:
            raise self._error
        return self._value


class MapResult(ApplyResult):
    """Ordered map over chunk refs."""

    def __init__(self, refs: List[Any], callback=None,
                 error_callback=None):
        self._refs = refs
        super().__init__(refs[0] if refs else None, callback,
                         error_callback)

    def _wait_thread(self):
        import ray_tpu

        try:
            chunks = ray_tpu.get(self._refs) if self._refs else []
            self._value = list(itertools.chain.from_iterable(chunks))
            if self._callback is not None:
                self._callback(self._value)
        except BaseException as e:  # noqa: BLE001
            self._error = e
            if self._error_callback is not None:
                self._error_callback(e)
        finally:
            self._done.set()


class Pool:
    """multiprocessing.Pool API over cluster actors (ref: util/
    multiprocessing/pool.py Pool). `processes=None` sizes the pool to the
    cluster's CPU count."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: Sequence = (), ray_address: Optional[str] = None):
        import ray_tpu

        ray_tpu.init(address=ray_address, ignore_reinit_error=True)
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources()
                                   .get("CPU", 1)))
        self._n = processes
        cls = ray_tpu.remote(_PoolActorCls)
        self._actors = [cls.options(num_cpus=1).remote(initializer,
                                                       tuple(initargs))
                        for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False

    # -- apply ----------------------------------------------------------
    def apply(self, func, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None) -> ApplyResult:
        self._check_running()
        actor = self._actors[next(self._rr)]
        ref = actor.run.remote(func, tuple(args), kwds or {})
        return ApplyResult(ref, callback, error_callback)

    # -- map ------------------------------------------------------------
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]
                ) -> List[List[tuple]]:
        items = [(x,) if not isinstance(x, tuple) else x
                 for x in iterable]
        if chunksize is None:
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _map_refs(self, func, chunks) -> List[Any]:
        return [self._actors[next(self._rr)].run_batch.remote(func, c)
                for c in chunks]

    def map(self, func, iterable, chunksize=None) -> list:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize=None, callback=None,
                  error_callback=None) -> MapResult:
        self._check_running()
        refs = self._map_refs(func, self._chunks(iterable, chunksize))
        return MapResult(refs, callback, error_callback)

    def starmap(self, func, iterable, chunksize=None) -> list:
        return self.map(func, [tuple(a) for a in iterable], chunksize)

    def starmap_async(self, func, iterable, chunksize=None,
                      callback=None, error_callback=None) -> MapResult:
        return self.map_async(func, [tuple(a) for a in iterable],
                              chunksize, callback, error_callback)

    def imap(self, func, iterable, chunksize=1):
        self._check_running()
        refs = self._map_refs(func, self._chunks(iterable, chunksize))
        import ray_tpu

        def gen():
            for ref in refs:         # submission order == yield order
                for v in ray_tpu.get(ref):
                    yield v

        return gen()

    def imap_unordered(self, func, iterable, chunksize=1):
        self._check_running()
        refs = self._map_refs(func, self._chunks(iterable, chunksize))
        import ray_tpu

        def gen():
            pending = list(refs)
            while pending:
                done, pending = ray_tpu.wait(pending, num_returns=1)
                for v in ray_tpu.get(done[0]):
                    yield v

        return gen()

    # -- lifecycle ------------------------------------------------------
    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def close(self):
        self._closed = True

    def terminate(self):
        import ray_tpu

        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors = []

    def join(self, timeout: float = 30.0):
        if not self._closed:
            raise ValueError("join() before close()")
        deadline = time.monotonic() + timeout
        while self._actors and time.monotonic() < deadline:
            time.sleep(0.05)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
