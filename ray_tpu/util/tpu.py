"""TPU slice helpers: worker-side introspection + driver-side slice gangs.

Worker-side mirrors `ray.util.accelerators.tpu` (ref: python/ray/util/
accelerators/tpu.py:7,19 — get_current_pod_name / get_current_pod_worker_count).
Driver-side adds what the reference leaves to user code: discovering slices
from the cluster resource view (every host of a slice carries `{tpu_name: 1}`
and worker 0 carries `TPU-{pod_type}-head: 1`, ref: _private/accelerators/
tpu.py:336-397) and reserving one slice atomically as a placement group so a
pjit gang lands inside a single ICI domain.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.distributed import accelerators
from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)

HEAD_PREFIX = "TPU-"
HEAD_SUFFIX = "-head"


# ---------------------------------------------------------------------------
# worker-side introspection (runs inside a task/actor on a TPU host)
# ---------------------------------------------------------------------------

def get_current_pod_name() -> Optional[str]:
    """Name of the TPU slice this host belongs to (ref: tpu.py:7)."""
    return accelerators.get_tpu_name()


def get_current_pod_worker_count() -> Optional[int]:
    """Number of hosts in this host's slice (ref: tpu.py:19)."""
    return accelerators.num_hosts_in_pod()


def get_num_tpu_chips_on_node() -> int:
    """TPU chips on THIS host (ref: tpu.py get_current_node_tpu_chips)."""
    import ray_tpu

    try:
        node_id = ray_tpu.get_runtime_context().get_node_id()
        for n in ray_tpu.nodes():
            if n["NodeID"] == node_id:
                return int(n["Resources"].get("TPU", 0))
    except Exception:  # noqa: BLE001 — not connected; probe locally
        pass
    try:
        from ray_tpu.core.distributed.resources import probe_tpu_count

        return int(probe_tpu_count())
    except Exception:  # noqa: BLE001
        return 0


# ---------------------------------------------------------------------------
# driver-side slice discovery + atomic reservation
# ---------------------------------------------------------------------------

class TpuSlice:
    """One discovered slice: its name resource, pod type, and host nodes."""

    def __init__(self, name: str, pod_type: str, node_ids: List[str],
                 chips_per_host: float):
        self.name = name
        self.pod_type = pod_type
        self.node_ids = node_ids
        self.chips_per_host = chips_per_host

    @property
    def num_hosts(self) -> int:
        return len(self.node_ids)

    def __repr__(self) -> str:
        return (f"TpuSlice({self.name!r}, {self.pod_type}, "
                f"{self.num_hosts} hosts)")


def list_slices(pod_type: Optional[str] = None) -> List[TpuSlice]:
    """Discover slices from node resources: a node carrying
    `TPU-{pod_type}-head` names its slice via the co-resident custom
    resource that other hosts of the slice share."""
    import ray_tpu

    nodes = ray_tpu.nodes()
    slices: List[TpuSlice] = []
    for n in nodes:
        if not n["Alive"]:
            continue
        head_keys = [k for k in n["Resources"]
                     if k.startswith(HEAD_PREFIX) and k.endswith(HEAD_SUFFIX)]
        for hk in head_keys:
            pt = hk[len(HEAD_PREFIX):-len(HEAD_SUFFIX)]
            if pod_type is not None and pt != pod_type:
                continue
            # The slice-name resource is the custom resource the head node
            # shares with its sibling hosts. Disambiguate from arbitrary
            # custom resources by membership count: prefer the key carried
            # by exactly the pod's host count, else the widest-shared key.
            expected = accelerators.num_hosts_in_pod(pt)
            best = None  # (score, name, members)
            for k in n["Resources"]:
                if k in ("CPU", "TPU", "memory") or k == hk:
                    continue
                if (k.startswith("accelerator_type:")
                        or (k.startswith(HEAD_PREFIX)
                            and k.endswith(HEAD_SUFFIX))):
                    continue
                peers = [m for m in nodes
                         if m["Alive"] and k in m["Resources"]]
                score = (2 if expected and len(peers) == expected else 1,
                         len(peers))
                if best is None or score > best[0]:
                    best = (score, k, peers)
            if best is None:
                continue
            name, members = best[1], best[2]
            chips = float(n["Resources"].get("TPU", 0))
            slices.append(TpuSlice(name, pt,
                                   [m["NodeID"] for m in members], chips))
    return slices


def reserve_slice(pod_type: str, timeout: float = 60.0,
                  cpus_per_host: float = 0.0) -> "SliceReservation":
    """Reserve ONE whole slice of `pod_type` atomically.

    The gang placement group puts one bundle on every host of a single
    slice ({slice_name: 1, TPU: chips} per host, STRICT_SPREAD), so two
    concurrent gangs can never interleave on the same slice — the second
    reservation waits until a slice is free (ref slice-gang pattern:
    _private/accelerators/tpu.py:382).
    """
    import time as _time

    from ray_tpu.core.distributed import accelerators as _acc

    expected_hosts = _acc.num_hosts_in_pod(pod_type)
    deadline = _time.monotonic() + timeout
    last_err = "no slices found"
    while _time.monotonic() < deadline:
        for sl in list_slices(pod_type):
            if expected_hosts and sl.num_hosts < expected_hosts:
                # Slice still booting (autoscaler launched it seconds
                # ago; some hosts haven't registered): reserving a
                # partial gang would hand out a PG with missing bundles.
                last_err = (f"slice {sl.name} has {sl.num_hosts}/"
                            f"{expected_hosts} hosts up")
                continue
            bundle = {sl.name: 1.0, "TPU": sl.chips_per_host}
            if cpus_per_host:
                bundle["CPU"] = cpus_per_host
            pg = placement_group([dict(bundle) for _ in range(sl.num_hosts)],
                                 strategy="STRICT_SPREAD")
            remaining = max(0.5, deadline - _time.monotonic())
            if pg.ready(timeout=min(5.0, remaining)):
                return SliceReservation(sl, pg)
            # Slice busy (another gang holds it): drop the pending PG and
            # try the next slice / retry.
            remove_placement_group(pg)
            last_err = f"slice {sl.name} busy"
        _time.sleep(0.2)
    raise TimeoutError(f"could not reserve a {pod_type} slice in "
                       f"{timeout}s: {last_err}")


class SliceReservation:
    """Holds a reserved slice; schedule gang members into `pg` bundles."""

    def __init__(self, tpu_slice: TpuSlice, pg: PlacementGroup):
        self.slice = tpu_slice
        self.pg = pg

    def release(self) -> None:
        remove_placement_group(self.pg)
