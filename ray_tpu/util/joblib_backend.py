"""Joblib backend: scikit-learn parallelism on the cluster.

Analogue of the reference's joblib integration (ref: python/ray/util/
joblib/ — register_ray() + RayBackend over the multiprocessing Pool
shim). After `register_ray_tpu()`, `joblib.parallel_backend("ray-tpu")`
routes every joblib.Parallel fan-out (e.g. sklearn GridSearchCV) through
cluster actors.
"""
from __future__ import annotations

from typing import Optional


def register_ray_tpu() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray-tpu", RayTpuBackend)


try:
    from joblib._parallel_backends import MultiprocessingBackend
except ImportError:  # pragma: no cover — joblib not installed
    MultiprocessingBackend = object


class RayTpuBackend(MultiprocessingBackend):
    """joblib backend whose pool is the actor-based Pool shim."""

    supports_timeout = True

    def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
        import ray_tpu

        ray_tpu.init(ignore_reinit_error=True)
        cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None or n_jobs == -1:
            return max(1, cpus)
        return max(1, min(n_jobs, cpus))

    def configure(self, n_jobs: int = 1, parallel=None, prefer=None,
                  require=None, **kwargs):
        n_jobs = self.effective_n_jobs(n_jobs)
        from ray_tpu.util.multiprocessing import Pool

        self._pool = Pool(processes=n_jobs)
        self.parallel = parallel
        return n_jobs

    def terminate(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.terminate()
            self._pool = None
