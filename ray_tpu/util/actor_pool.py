"""ActorPool: fan work over a fixed set of actors.

Analogue of `ray.util.ActorPool` (ref: python/ray/util/actor_pool.py —
submit/map/map_unordered over idle actors, get_next/get_next_unordered
consumption).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending_order: List[Any] = []   # submission order

    def has_free(self) -> bool:
        return bool(self._idle)

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; blocks-free (raises if no idle
        actor — push after a get_next to recycle)."""
        if not self._idle:
            raise ValueError("no idle actors; consume results first")
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._pending_order.append(ref)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in SUBMISSION order. A timeout raises BEFORE any
        state changes, so the caller can retry and the busy actor is not
        handed new work."""
        import ray_tpu

        if not self._pending_order:
            raise StopIteration("no pending results")
        ref = self._pending_order[0]
        if timeout is not None:
            done, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
            if not done:
                raise TimeoutError("next result not ready within timeout")
        self._pending_order.pop(0)
        actor = self._future_to_actor.pop(ref)
        try:
            return ray_tpu.get(ref)
        finally:
            self._idle.append(actor)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next COMPLETED result, any order."""
        import ray_tpu

        if not self._future_to_actor:
            raise StopIteration("no pending results")
        done, _ = ray_tpu.wait(list(self._future_to_actor),
                               num_returns=1, timeout=timeout)
        if not done:
            raise TimeoutError("no result within timeout")
        ref = done[0]
        actor = self._future_to_actor.pop(ref)
        self._pending_order.remove(ref)
        self._idle.append(actor)
        return ray_tpu.get(ref)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]):
        """Ordered streaming map (ref: ActorPool.map)."""
        values = list(values)
        i = 0
        while i < len(values) or self.has_next():
            while i < len(values) and self.has_free():
                self.submit(fn, values[i])
                i += 1
            if self.has_next():
                yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        values = list(values)
        i = 0
        while i < len(values) or self.has_next():
            while i < len(values) and self.has_free():
                self.submit(fn, values[i])
                i += 1
            if self.has_next():
                yield self.get_next_unordered()

    def push(self, actor: Any) -> None:
        self._idle.append(actor)

    def pop_idle(self) -> Any:
        return self._idle.pop(0) if self._idle else None
