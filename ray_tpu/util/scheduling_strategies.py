"""Scheduling strategy classes (ref: python/ray/util/
scheduling_strategies.py)."""
from ray_tpu.core.task_spec import (
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "DefaultSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "SpreadSchedulingStrategy",
]
