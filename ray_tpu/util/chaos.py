"""Chaos harness: random worker/node killers for fault-injection tests.

ref: python/ray/_private/test_utils.py:1429-1640 (ResourceKillerActor /
WorkerKillerActor / NodeKillerActor + get_and_run_resource_killer).
Runs on the driver as a background thread issuing kill RPCs to node
daemons — the workload under test must complete correctly anyway.
"""
from __future__ import annotations

import random
import threading
import time
from typing import List, Optional


class WorkerKiller:
    """Periodically SIGKILLs a random task worker somewhere in the cluster.

    Usage::

        killer = WorkerKiller(interval_s=0.4)
        killer.start()
        ... run workload ...
        kills = killer.stop()
    """

    def __init__(self, interval_s: float = 0.5, seed: int = 0,
                 include_actor_workers: bool = False):
        self.interval_s = interval_s
        self.include_actor_workers = include_actor_workers
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills: List[dict] = []

    # -- plumbing -----------------------------------------------------------
    def _daemon_addresses(self) -> List[str]:
        import ray_tpu

        return [n["Address"] for n in ray_tpu.nodes() if n["Alive"]]

    def _kill_one(self) -> Optional[dict]:
        from ray_tpu.api import _global_worker
        from ray_tpu.core.distributed.rpc import SyncRpcClient

        w = _global_worker()
        addrs = self._daemon_addresses()
        self._rng.shuffle(addrs)
        for addr in addrs:
            try:
                client = SyncRpcClient(addr, w.loop_thread)
                reply = client.call(
                    "NodeDaemon", "kill_random_worker",
                    include_actor_workers=self.include_actor_workers,
                    seed=self._rng.randrange(1 << 30), timeout=10)
                client.close()
            except Exception:  # noqa: BLE001 — daemon itself may be dying
                continue
            if reply.get("ok"):
                return reply
        return None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            hit = self._kill_one()
            if hit:
                self.kills.append(hit)

    # -- public -------------------------------------------------------------
    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> List[dict]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills


class NodeKiller:
    """Kills whole (non-head) nodes of a cluster_utils.Cluster — gang /
    lineage recovery must absorb it (ref: NodeKillerActor,
    test_utils.py:1497)."""

    def __init__(self, cluster, interval_s: float = 2.0, seed: int = 0,
                 max_kills: int = 1):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills: List[str] = []

    def _loop(self) -> None:
        while (not self._stop.wait(self.interval_s)
               and len(self.kills) < self.max_kills):
            victims = [n for n in self.cluster.nodes
                       if n is not self.cluster.head]
            if not victims:
                continue
            node = self._rng.choice(victims)
            try:
                self.cluster.remove_node(node)
                self.kills.append(node.node_id)
            except Exception:  # noqa: BLE001
                pass

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> List[str]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills
