"""Chaos harness: random worker/node killers for fault-injection tests.

ref: python/ray/_private/test_utils.py:1429-1640 (ResourceKillerActor /
WorkerKillerActor / NodeKillerActor + get_and_run_resource_killer).
Runs on the driver as a background thread issuing kill RPCs to node
daemons — the workload under test must complete correctly anyway.
"""
from __future__ import annotations

import random
import signal
import threading
import time
from typing import List, Optional


class WorkerKiller:
    """Periodically SIGKILLs a random task worker somewhere in the cluster.

    Usage::

        killer = WorkerKiller(interval_s=0.4)
        killer.start()
        ... run workload ...
        kills = killer.stop()
    """

    def __init__(self, interval_s: float = 0.5, seed: int = 0,
                 include_actor_workers: bool = False):
        self.interval_s = interval_s
        self.include_actor_workers = include_actor_workers
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills: List[dict] = []

    # -- plumbing -----------------------------------------------------------
    def _daemon_addresses(self) -> List[str]:
        import ray_tpu

        return [n["Address"] for n in ray_tpu.nodes() if n["Alive"]]

    def _kill_one(self) -> Optional[dict]:
        from ray_tpu.api import _global_worker
        from ray_tpu.core.distributed.rpc import SyncRpcClient

        w = _global_worker()
        addrs = self._daemon_addresses()
        self._rng.shuffle(addrs)
        for addr in addrs:
            try:
                client = SyncRpcClient(addr, w.loop_thread)
                reply = client.call(
                    "NodeDaemon", "kill_random_worker",
                    include_actor_workers=self.include_actor_workers,
                    seed=self._rng.randrange(1 << 30), timeout=10)
                client.close()
            except Exception:  # noqa: BLE001 — daemon itself may be dying
                continue
            if reply.get("ok"):
                return reply
        return None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            hit = self._kill_one()
            if hit:
                self.kills.append(hit)

    # -- public -------------------------------------------------------------
    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> List[dict]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills


class NodeKiller:
    """Kills whole (non-head) nodes of a cluster_utils.Cluster — gang /
    lineage recovery must absorb it (ref: NodeKillerActor,
    test_utils.py:1497)."""

    def __init__(self, cluster, interval_s: float = 2.0, seed: int = 0,
                 max_kills: int = 1):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills: List[str] = []

    def _loop(self) -> None:
        while (not self._stop.wait(self.interval_s)
               and len(self.kills) < self.max_kills):
            victims = [n for n in self.cluster.nodes
                       if n is not self.cluster.head]
            if not victims:
                continue
            node = self._rng.choice(victims)
            try:
                self.cluster.remove_node(node)
                self.kills.append(node.node_id)
            except Exception:  # noqa: BLE001
                pass

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> List[str]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills


# ---- deterministic gang-targeted injectors ----------------------------
#
# The random killers above answer "does the cluster survive churn?"; the
# elastic-training tests need the sharper question "does the gang survive
# THIS rank failing THIS way?". These target one rank by its worker pid
# (WorkerGroup.pids) and fan the signal across every node daemon — only
# the daemon owning the pid acts on it.

def _signal_pid(pid: int, sig: int) -> bool:
    from ray_tpu.api import _global_worker
    from ray_tpu.core.distributed.rpc import SyncRpcClient

    import ray_tpu

    w = _global_worker()
    for n in ray_tpu.nodes():
        if not n["Alive"]:
            continue
        try:
            client = SyncRpcClient(n["Address"], w.loop_thread)
            try:
                reply = client.call("NodeDaemon", "signal_worker",
                                    sig=int(sig), pid=pid, timeout=10)
            finally:
                client.close()
        except Exception:  # noqa: BLE001 — that daemon may be dying
            continue
        if reply.get("ok"):
            return True
    return False


def kill_rank(group, rank: int) -> bool:
    """SIGKILL one rank's worker process mid-step (death injection)."""
    pid = group.pids[rank]
    return pid is not None and _signal_pid(pid, signal.SIGKILL)


def sigstop_rank(group, rank: int) -> bool:
    """Freeze one rank (SIGSTOP): a deterministic straggler that still
    holds its lease — exactly what the hang watchdog must catch."""
    pid = group.pids[rank]
    return pid is not None and _signal_pid(pid, signal.SIGSTOP)


def sigcont_rank(group, rank: int) -> bool:
    """Thaw a SIGSTOPped rank."""
    pid = group.pids[rank]
    return pid is not None and _signal_pid(pid, signal.SIGCONT)


class DelayedPartition:
    """SIGSTOPs one cluster_utils node's DAEMON process after a delay —
    the node falls silent (misses heartbeats, drops RPCs) without its
    workers dying: a network partition as the control plane sees one.
    heal() SIGCONTs it; stop() heals and joins."""

    def __init__(self, node, delay_s: float = 1.0):
        self.node = node
        self.delay_s = delay_s
        self._timer: Optional[threading.Timer] = None
        self.partitioned = threading.Event()

    def start(self) -> "DelayedPartition":
        self._timer = threading.Timer(self.delay_s, self._partition)
        self._timer.daemon = True
        self._timer.start()
        return self

    def _partition(self) -> None:
        try:
            self.node.proc.send_signal(signal.SIGSTOP)
            self.partitioned.set()
        except Exception:  # noqa: BLE001 — node already gone
            pass

    def heal(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
        if self.partitioned.is_set():
            try:
                self.node.proc.send_signal(signal.SIGCONT)
            except Exception:  # noqa: BLE001
                pass
            self.partitioned.clear()

    def stop(self) -> None:
        self.heal()
