"""State API: programmatic cluster introspection.

Analogue of the reference state SDK (ref: python/ray/util/state/api.py —
list_tasks/list_actors/list_nodes/list_placement_groups/list_jobs,
backed by the GCS task-event and registry tables; CLI in state_cli.py —
ours is `ray-tpu list ...`). Each call is one GCS RPC through the
ambient driver connection; `filters` are (key, predicate, value) tuples
with predicate "=", "!=", "contains" or "prefix" (the reference surface
plus the substring forms `ray-tpu stack --task` name-matching needs).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

Filter = Tuple[str, str, Any]


def _gcs():
    from ray_tpu.api import _global_worker

    return _global_worker().gcs


def _apply_filters(rows: List[dict],
                   filters: Optional[List[Filter]]) -> List[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, pred, value in filters:
            have = row.get(key)
            if pred == "=":
                ok = have == value
            elif pred == "!=":
                ok = have != value
            elif pred == "contains":
                ok = have is not None and str(value) in str(have)
            elif pred == "prefix":
                ok = (have is not None
                      and str(have).startswith(str(value)))
            else:
                raise ValueError(
                    f"unsupported predicate {pred!r} "
                    f"(valid: '=', '!=', 'contains', 'prefix')")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def list_nodes(filters: Optional[List[Filter]] = None,
               limit: int = 10000) -> List[dict]:
    rows = _gcs().call("NodeInfo", "list_nodes", timeout=30)
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters: Optional[List[Filter]] = None,
                limit: int = 10000) -> List[dict]:
    rows = _gcs().call("ActorManager", "list_actors", timeout=30)
    return _apply_filters(rows, filters)[:limit]


def list_tasks(filters: Optional[List[Filter]] = None,
               limit: int = 10000) -> List[dict]:
    """Task attempts with their full status-transition history: each row
    carries `state_ts` ({state: wall time} for SUBMITTED/LEASED/RUNNING/
    FINISHED|FAILED) merged across the driver's and executor's reports.
    Use task_events_stats() for how complete this window is."""
    rows = _gcs().call("TaskEvents", "list_events", limit=limit,
                       timeout=30)
    rows = [r for r in rows if r.get("kind") not in ("span", "profile")]
    return _apply_filters(rows, filters)[:limit]


def get_task(task_id: str) -> List[dict]:
    """All stored attempts of one task (ref: `ray get tasks <id>`)."""
    return _gcs().call("TaskEvents", "get_task", task_id=task_id,
                       timeout=30)


def task_events_stats() -> dict:
    """Completeness accounting for the task-event window: stored counts
    plus everything dropped worker-side (bounded ring under a dead GCS)
    or evicted GCS-side (per-job cap, finished-job GC)."""
    return _gcs().call("TaskEvents", "stats", timeout=30)


def list_placement_groups(filters: Optional[List[Filter]] = None,
                          limit: int = 10000) -> List[dict]:
    rows = _gcs().call("PlacementGroups", "list_pgs", timeout=30)
    return _apply_filters(rows, filters)[:limit]


def list_jobs(filters: Optional[List[Filter]] = None,
              limit: int = 10000) -> List[dict]:
    rows = _gcs().call("JobManager", "list_jobs", timeout=30)
    return _apply_filters(rows, filters)[:limit]


def list_workers(filters: Optional[List[Filter]] = None,
                 limit: int = 10000) -> List[dict]:
    from ray_tpu.api import _global_worker
    from ray_tpu.core.distributed.rpc import SyncRpcClient

    w = _global_worker()
    rows: List[dict] = []
    for n in list_nodes():
        if not n["alive"]:
            continue
        client = SyncRpcClient(n["address"], w.loop_thread)
        try:
            for worker in client.call("NodeDaemon", "list_workers",
                                      timeout=10):
                worker["node_id"] = n["node_id"]
                rows.append(worker)
        except Exception:  # noqa: BLE001 node mid-restart
            continue
        finally:
            client.close()
    return _apply_filters(rows, filters)[:limit]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """Per-task-name state counts (ref: `ray summary tasks`), computed
    GCS-side over the full stored window (not a list_tasks page)."""
    return _gcs().call("TaskEvents", "summarize", timeout=30)["tasks"]


def task_summary() -> dict:
    """summarize_tasks plus the completeness meta: {"tasks": per-name
    state counts, "completeness": stored/evicted/dropped accounting} —
    the honest version (a capped window must say it is a window)."""
    return _gcs().call("TaskEvents", "summarize", timeout=30)


def get_actor(actor_id: str) -> Optional[dict]:
    return _gcs().call("ActorManager", "get_actor", actor_id=actor_id,
                       timeout=30)


def dump_stacks(node_id: Optional[str] = None,
                worker_id: Optional[str] = None,
                pids: Optional[List[int]] = None) -> List[dict]:
    """Signal-safe all-thread stack dumps from every (matching) live
    worker in the cluster, fanned out by the GCS Diagnosis service —
    works even for workers wedged in GIL-holding native code (the
    faulthandler/SIGUSR1 path, not in-process sampling)."""
    return _gcs().call("Diagnosis", "dump_stacks", node_id=node_id,
                       worker_id=worker_id, pids=pids, timeout=60)


def summarize_stacks(node_id: Optional[str] = None) -> dict:
    """Cluster stack dump grouped by identical thread stacks: the
    one-line hang answer ("412/512 workers blocked in all_reduce at
    collective.py:...") under "groups", raw per-node dumps under
    "nodes"."""
    return _gcs().call("Diagnosis", "summarize_stacks", node_id=node_id,
                       timeout=60)


def hung_tasks() -> List[dict]:
    """Attempts the hung-task watchdog flagged that are still RUNNING
    (also surfaced under cluster_status()["observability"])."""
    return _gcs().call("Metrics", "cluster_summary",
                       timeout=30).get("hung_tasks", [])


def elastic_events(limit: int = 100) -> List[dict]:
    """Elastic-training plane events (gang restarts, shrinks, grows,
    replacement timeouts) emitted by the ElasticSupervisor via the GCS
    event log."""
    return _gcs().call("EventLog", "list_events", source="elastic",
                       limit=limit, timeout=30)


def cluster_events(kind: Optional[str] = None,
                   node_id: Optional[str] = None,
                   since: Optional[float] = None,
                   until: Optional[float] = None,
                   limit: int = 200) -> List[dict]:
    """Cluster flight-recorder timeline: durable state transitions
    (node join/death/re-registration, serve failover, drain + KV
    migration, autoscale and elastic resizes, PG repair) oldest-first.
    `kind` is a prefix match ("node" matches node.join/node.death...);
    `since`/`until` are wall-clock bounds. Survives GCS restarts."""
    return _gcs().call("FlightRecorder", "list_events", kind=kind,
                       node_id=node_id, since=since, until=until,
                       limit=limit, timeout=30)


def gcs_load() -> dict:
    """GCS control-plane self-observability: per-service x per-caller-
    component load shares (requests/bytes/handler time) since GCS boot,
    the slow-handler audit, the event-loop audit, and flight-journal
    stats. Same blob as cluster_status()["observability"]["gcs"]."""
    return _gcs().call("Metrics", "gcs_load", timeout=30)


def doctor() -> dict:
    """One fused cluster health report: ranked findings over federated
    metrics freshness, hung tasks, task-event loss, GCS load shares,
    event-loop lag, and recent flight-recorder entries. Each finding
    has a severity, a score (higher = worse) and an actionable hint."""
    return _gcs().call("Metrics", "doctor", timeout=30)


def placement_groups() -> List[dict]:
    """All placement groups with gang state: per-PG `placed`/
    `bundle_count` shows a gang mid-repair (holes being re-reserved)."""
    return _gcs().call("PlacementGroups", "list_pgs", timeout=30)


def cluster_status() -> dict:
    """The autoscaler's view: demand, idle times, resource requests —
    enriched with the observability rollup (metrics federation
    freshness, task-event completeness, watchdog-flagged hung tasks)
    under "observability"."""
    status = _gcs().call("AutoscalerState", "get_cluster_status",
                         timeout=30)
    try:
        status["observability"] = _gcs().call("Metrics", "cluster_summary",
                                              timeout=30)
    except Exception:  # noqa: BLE001 — pre-federation GCS
        pass
    return status


def cluster_metrics() -> str:
    """The GCS's federated Prometheus exposition: every node's last
    syncer-shipped snapshot merged, node-labelled."""
    return _gcs().call("Metrics", "federated_text", timeout=30)


def serve_summary() -> dict:
    """Serving-plane observability rollup: per-app replica gauges plus
    the latency/counter view mined from the federated serve metrics
    ({"apps", "latency" (ttft/itl/phase means), "counters"}).  Same
    blob as cluster_status()["observability"]["serve"]."""
    return _gcs().call("Metrics", "cluster_summary",
                       timeout=30).get("serve", {})


def train_runs() -> dict:
    """Train-plane goodput view: per-run wall-time split (productive
    compute vs data-stall vs sync-stall vs checkpoint vs
    lost-to-restart), current step rate, cross-rank skew window with
    blame-rank attribution, restart accounting, and the optional MFU
    estimate ({run: {...}}). Same blob as
    cluster_status()["observability"]["train"]["runs"]."""
    return _gcs().call("Train", "summary", timeout=30).get("runs", {})


def train_trace(run_id: str, filename: Optional[str] = None) -> str:
    """Dump one training run's per-rank step/phase span tracks as a
    chrome/perfetto trace; returns the written path. Convenience
    re-export of ray_tpu.util.timeline.train_trace."""
    from ray_tpu.util.timeline import train_trace as _tt

    return _tt(run_id, filename=filename)


def request_trace(request_id: str,
                  filename: Optional[str] = None) -> str:
    """Dump one serve request's end-to-end span track (proxy -> handle
    -> replica -> engine) as a chrome/perfetto trace; returns the
    written path. Convenience re-export of
    ray_tpu.util.timeline.request_trace."""
    from ray_tpu.util.timeline import request_trace as _rt

    return _rt(request_id, filename=filename)
