"""Distributed queue: a FIFO shared between tasks/actors.

Analogue of `ray.util.queue.Queue` (ref: python/ray/util/queue.py — an
actor-backed asyncio queue with put/get/qsize and the Empty/Full
exceptions of the stdlib queue module).
"""
from __future__ import annotations

import asyncio
from typing import Any, List, Optional

from queue import Empty, Full  # noqa: F401 — re-exported, stdlib parity


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except (TimeoutError, asyncio.TimeoutError):
            return False

    async def put_nowait(self, item: Any) -> bool:
        # Async like everything else: a sync method on an async actor
        # runs on a pool thread and would mutate the loop-bound
        # asyncio.Queue from the wrong thread (lost wakeups).
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None) -> tuple:
        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except (TimeoutError, asyncio.TimeoutError):
            return False, None

    async def get_nowait(self) -> tuple:
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()


class Queue:
    """Driver/worker-shareable FIFO; pickles by actor handle, so any
    process holding it talks to the same queue actor."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict]
                 = None, _actor=None):
        import ray_tpu

        if _actor is not None:
            self._actor = _actor
            return
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 16)
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import ray_tpu

        if not block:
            if not ray_tpu.get(self._actor.put_nowait.remote(item)):
                raise Full
            return
        if not ray_tpu.get(self._actor.put.remote(item, timeout)):
            raise Full

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        import ray_tpu

        if not block:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote())
        else:
            ok, item = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.full.remote())

    def shutdown(self) -> None:
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:  # noqa: BLE001
            pass

    def __reduce__(self):
        # By handle: every deserialized copy talks to the SAME actor
        # (and must not spawn a fresh queue via __init__).
        return (_queue_from_actor, (self._actor,))


def _queue_from_actor(actor) -> "Queue":
    return Queue(_actor=actor)
