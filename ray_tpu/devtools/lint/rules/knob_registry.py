"""knob-registry: every ``RAY_TPU_*`` env read goes through core/config.py,
and the README knob docs stay in sync with the registry (both directions).

Detected read shapes (outside the registry file)::

    os.environ.get("RAY_TPU_X", ...)
    os.environ["RAY_TPU_X"]          # Load context only; writes are fine
    os.getenv("RAY_TPU_X")
    environ.get("RAY_TPU_X")         # from os import environ

Suppression: ``# lint: allow-knob -- <reason>`` on the read (bootstrap vars
that must be readable before/without the config singleton).

README sync: every ``Config`` field must have its ``RAY_TPU_<FIELD>`` env
name mentioned in README.md, and every ``RAY_TPU_*`` token in README must be
a registered knob, a prefix wildcard ending in ``_`` matching at least one
knob, or listed in :data:`NON_KNOB_ENV` (documented env vars that are not
config knobs, with the reason they are exempt).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu.devtools.lint.engine import LintContext, PyFile, Rule, Violation

CONFIG_REL = "ray_tpu/core/config.py"

# Env vars legitimately documented in README that are NOT Config knobs.
# Value = why the exemption exists. Internal bootstrap vars (set by one
# process, read by its child before config exists) belong here only if the
# README documents them.
NON_KNOB_ENV: Dict[str, str] = {
    "RAY_TPU_REMOTE": "C++ preprocessor macro in the native task API, not an env var",
    "RAY_TPU_SCHED_FUZZ_MAX_MS": "schedule-fuzz harness reads env per call so seed sweeps work mid-process",
    "RAY_TPU_SCHED_FUZZ_SEED": "schedule-fuzz harness reads env per call so seed sweeps work mid-process",
}

_ENV_NAME_RE = re.compile(r"RAY_TPU_[A-Z0-9_]+_?")


@dataclass
class _EnvRead:
    name: str
    line: int


class _EnvReadVisitor(ast.NodeVisitor):
    """Collect RAY_TPU_* literal env reads in one module."""

    def __init__(self) -> None:
        self.reads: List[_EnvRead] = []
        self._environ_aliases = {"environ"}
        self._getenv_aliases = {"getenv"}

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "os":
            for alias in node.names:
                if alias.name == "environ":
                    self._environ_aliases.add(alias.asname or alias.name)
                elif alias.name == "getenv":
                    self._getenv_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def _is_environ(self, node: ast.expr) -> bool:
        text = _unparse(node)
        return text.endswith(".environ") or text in self._environ_aliases

    def _literal_ray_tpu(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value.startswith("RAY_TPU_"):
                return node.value
        return None

    def visit_Call(self, node: ast.Call) -> None:
        name = None
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in ("get", "pop", "setdefault") and self._is_environ(func.value):
                if node.args:
                    name = self._literal_ray_tpu(node.args[0])
            elif func.attr == "getenv" and _unparse(func.value) == "os":
                if node.args:
                    name = self._literal_ray_tpu(node.args[0])
        elif isinstance(func, ast.Name) and func.id in self._getenv_aliases:
            if node.args:
                name = self._literal_ray_tpu(node.args[0])
        if name:
            self.reads.append(_EnvRead(name, node.lineno))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and self._is_environ(node.value):
            sl = node.slice
            name = self._literal_ray_tpu(sl) if isinstance(sl, ast.Constant) else None
            if name:
                self.reads.append(_EnvRead(name, node.lineno))
        self.generic_visit(node)


def _unparse(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


@dataclass
class _Knob:
    field: str
    env: str
    line: int
    default_src: str
    section: str


def parse_registry(config_file: PyFile) -> List[_Knob]:
    """Extract ``Config`` dataclass fields + their env names, source defaults,
    and the ``# ---- section ----`` group each belongs to."""
    tree = config_file.tree
    if tree is None:
        return []
    sections: List[tuple] = []  # (line, title)
    for i, line in enumerate(config_file.source.splitlines(), start=1):
        m = re.match(r"\s*#\s*-{2,}\s*(.*?)\s*-{2,}\s*$", line)
        if m and m.group(1):
            sections.append((i, m.group(1)))
    knobs: List[_Knob] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                    field_name = stmt.target.id
                    if field_name.startswith("_"):
                        continue
                    default_src = (
                        _unparse(stmt.value) if stmt.value is not None else ""
                    )
                    section = ""
                    for line_no, title in sections:
                        if line_no < stmt.lineno:
                            section = title
                    knobs.append(
                        _Knob(
                            field=field_name,
                            env=f"RAY_TPU_{field_name.upper()}",
                            line=stmt.lineno,
                            default_src=default_src,
                            section=section,
                        )
                    )
            break
    return knobs


def knob_table_markdown(ctx: LintContext) -> str:
    """Render the README knob table from the live registry (the docs artifact
    this rule validates)."""
    config_file = ctx.get_file(CONFIG_REL)
    if config_file is None:
        return ""
    knobs = parse_registry(config_file)
    out: List[str] = []
    current = None
    for k in knobs:
        if k.section != current:
            current = k.section
            out.append("")
            out.append(f"#### {current or 'Other'}")
            out.append("")
            out.append("| knob | env override | default |")
            out.append("| --- | --- | --- |")
        default = k.default_src.replace("|", "\\|")
        out.append(f"| `{k.field}` | `{k.env}` | `{default}` |")
    return "\n".join(out).strip() + "\n"


class KnobRegistryRule(Rule):
    name = "knob-registry"
    allow_token = "knob"
    description = (
        "RAY_TPU_* env reads must go through core/config.py; README knob "
        "docs must match the registry in both directions"
    )

    def check(self, ctx: LintContext) -> List[Violation]:
        out: List[Violation] = []
        config_file = ctx.get_file(CONFIG_REL)
        if config_file is None:
            return [
                Violation(
                    rule=self.name,
                    path=CONFIG_REL,
                    line=1,
                    message="config registry file not found under lint root",
                )
            ]
        knobs = parse_registry(config_file)
        env_names = {k.env for k in knobs}

        # 1) stray env reads outside the registry
        for f in ctx.package_files():
            if f.rel == CONFIG_REL or f.tree is None:
                continue
            visitor = _EnvReadVisitor()
            visitor.visit(f.tree)
            for read in visitor.reads:
                hint = ""
                if read.name in env_names:
                    fld = read.name[len("RAY_TPU_"):].lower()
                    hint = f" (read get_config().{fld} instead)"
                out.append(
                    Violation(
                        rule=self.name,
                        path=f.rel,
                        line=read.line,
                        message=(
                            f"os.environ read of {read.name} outside the "
                            f"config registry{hint}"
                        ),
                    )
                )

        # 2) README <-> registry sync
        readme = ctx.root / "README.md"
        if readme.is_file():
            text = readme.read_text(encoding="utf-8", errors="replace")
            doc_tokens: Dict[str, int] = {}
            for i, line in enumerate(text.splitlines(), start=1):
                for m in _ENV_NAME_RE.finditer(line):
                    doc_tokens.setdefault(m.group(0), i)
            documented = set(doc_tokens)
            # knobs missing from the docs
            for k in knobs:
                covered = k.env in documented or any(
                    t.endswith("_") and k.env.startswith(t) for t in documented
                )
                if not covered:
                    out.append(
                        Violation(
                            rule=self.name,
                            path=CONFIG_REL,
                            line=k.line,
                            message=(
                                f"knob '{k.field}' ({k.env}) is not documented "
                                "in README.md (regenerate the knob table: "
                                "ray-tpu lint --knob-table)"
                            ),
                        )
                    )
            # documented names with no backing knob
            for token, line_no in sorted(doc_tokens.items()):
                if token.endswith("_"):
                    if any(e.startswith(token) for e in env_names) or any(
                        e.startswith(token) for e in NON_KNOB_ENV
                    ):
                        continue
                elif token in env_names or token in NON_KNOB_ENV:
                    continue
                out.append(
                    Violation(
                        rule=self.name,
                        path="README.md",
                        line=line_no,
                        message=(
                            f"README documents {token} but no such knob is "
                            "registered in core/config.py (orphan doc entry)"
                        ),
                    )
                )
        return out
