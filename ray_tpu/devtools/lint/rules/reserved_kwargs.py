"""reserved-kwargs: user-facing entrypoints (functions/classes decorated
with ``@ray_tpu.remote`` or ``@serve.deployment``, and methods of decorated
classes) must not declare parameters that shadow the serve-reserved kwargs
the framework strips or injects on the call path:

- ``_request_id``   (stripped by DeploymentHandle before dispatch)
- ``_trace`` / ``_serve_trace``  (trace context injected by the replica)
- ``_serve_resume`` (stream-resume cursor injected on reconnect)

A parameter with one of these names either never receives user values (the
framework pops it) or collides with the injected value — both are silent
API bugs.  Framework-internal resume-aware callables can opt in with
``# lint: allow-reserved-kwarg -- <reason>`` on the ``def`` line.

Scanned scope: the ``ray_tpu`` package and ``examples/``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu.devtools.lint.engine import LintContext, PyFile, Rule, Violation

RESERVED = ("_request_id", "_trace", "_serve_trace", "_serve_resume")
_ENTRYPOINT_DECORATORS = {"remote", "deployment"}


def _decorator_name(dec: ast.expr) -> Optional[str]:
    if isinstance(dec, ast.Call):
        dec = dec.func
    if isinstance(dec, ast.Attribute):
        return dec.attr
    if isinstance(dec, ast.Name):
        return dec.id
    return None


def _is_entrypoint_decorated(node) -> bool:
    return any(
        _decorator_name(d) in _ENTRYPOINT_DECORATORS
        for d in getattr(node, "decorator_list", [])
    )


def _reserved_params(fn) -> List[ast.arg]:
    args = fn.args
    every = list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    if args.vararg:
        every.append(args.vararg)
    if args.kwarg:
        every.append(args.kwarg)
    return [a for a in every if a.arg in RESERVED]


class ReservedKwargsRule(Rule):
    name = "reserved-kwargs"
    allow_token = "reserved-kwarg"
    description = (
        "deployment/actor entrypoints must not shadow serve-reserved "
        "kwargs (_request_id/_trace/_serve_trace/_serve_resume)"
    )

    def check(self, ctx: LintContext) -> List[Violation]:
        out: List[Violation] = []
        files = list(ctx.package_files())
        if (ctx.root / "ray_tpu").is_dir():
            files += ctx.py_files("examples/")
        for f in files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if _is_entrypoint_decorated(node):
                        self._flag(f, node, node.name, out)
                elif isinstance(node, ast.ClassDef) and _is_entrypoint_decorated(node):
                    for member in node.body:
                        if not isinstance(
                            member, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            continue
                        if member.name.startswith("__") and member.name != "__call__":
                            continue
                        self._flag(f, member, f"{node.name}.{member.name}", out)
        return out

    def _flag(self, f: PyFile, fn, qualname: str, out: List[Violation]) -> None:
        for param in _reserved_params(fn):
            out.append(
                Violation(
                    rule=self.name,
                    path=f.rel,
                    line=fn.lineno,
                    message=(
                        f"{qualname} declares parameter '{param.arg}', which "
                        "shadows a serve-reserved kwarg the framework strips "
                        "or injects — rename it (or allowlist a resume-aware "
                        "callable with a reason)"
                    ),
                )
            )
