"""wire-typed-errors: every error that can cross the RPC boundary must be a
``RayTpuError`` subclass declared in ``ray_tpu/exceptions.py`` that survives
``pickle.loads(pickle.dumps(e))`` preserving ``args`` and custom fields.

Two checks, generated from the class tree — no hand-maintained list:

1. **round-trip probe** (dynamic): ``exceptions.py`` is loaded as a
   standalone module (it only imports stdlib, so this works for fixture
   trees too), every class reachable from ``RayTpuError`` is instantiated
   from its ``__init__`` signature with probe values, pickled, unpickled,
   and compared on type / ``args`` / instance ``__dict__``.  The classic
   failure is an ``__init__`` signature incompatible with pickle's default
   ``Exception.__reduce__`` (which replays ``cls(*args)``).

2. **declaration locality** (static): a class elsewhere in the package that
   subclasses a tree class is flagged — the round-trip probe cannot see it,
   and workers classify errors by ``isinstance`` against the canonical tree.
"""

from __future__ import annotations

import ast
import importlib.util
import pickle
import sys
from typing import Dict, List, Optional, Set

from ray_tpu.devtools.lint.engine import LintContext, PyFile, Rule, Violation

EXC_REL = "ray_tpu/exceptions.py"
ROOT_CLASS = "RayTpuError"

_PROBE_VALUES = {
    str: "probe",
    int: 7,
    float: 1.5,
    bool: True,
    bytes: b"probe",
}


def _tree_class_names(exc_file: PyFile) -> Dict[str, int]:
    """Class names reachable from RayTpuError in exceptions.py -> def line."""
    tree = exc_file.tree
    if tree is None:
        return {}
    classes: Dict[str, List[str]] = {}
    linenos: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            bases = []
            for b in node.bases:
                if isinstance(b, ast.Name):
                    bases.append(b.id)
                elif isinstance(b, ast.Attribute):
                    bases.append(b.attr)
            classes[node.name] = bases
            linenos[node.name] = node.lineno
    in_tree: Set[str] = set()
    if ROOT_CLASS in classes:
        in_tree.add(ROOT_CLASS)
        changed = True
        while changed:
            changed = False
            for name, bases in classes.items():
                if name not in in_tree and any(b in in_tree for b in bases):
                    in_tree.add(name)
                    changed = True
    return {name: linenos[name] for name in in_tree}


def load_exceptions_module(exc_path) -> object:
    """Load an exceptions.py as a standalone module (registered in
    sys.modules so pickle-by-reference round-trips within the process)."""
    mod_name = f"_ray_tpu_lint_exc_{abs(hash(str(exc_path)))}"
    if mod_name in sys.modules:
        return sys.modules[mod_name]
    spec = importlib.util.spec_from_file_location(mod_name, exc_path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = module
    try:
        spec.loader.exec_module(module)
    except Exception:
        del sys.modules[mod_name]
        raise
    return module


def _build_instance(cls):
    """Instantiate *cls* from its __init__ signature using probe values."""
    import inspect

    sig = inspect.signature(cls.__init__)
    args = []
    kwargs = {}
    for name, param in list(sig.parameters.items())[1:]:  # skip self
        if param.kind in (param.VAR_POSITIONAL, param.VAR_KEYWORD):
            continue
        ann = param.annotation
        value = None
        known = False
        if ann in _PROBE_VALUES:
            value, known = _PROBE_VALUES[ann], True
        elif isinstance(ann, str):
            for t, v in _PROBE_VALUES.items():
                if ann == t.__name__:
                    value, known = v, True
                    break
        if param.default is not param.empty and not known:
            # keep defaults for params we can't type (e.g. Optional[...]
            # causes that are deliberately dropped from the wire)
            continue
        if not known:
            value = "probe:%s" % name
        if param.kind == param.POSITIONAL_ONLY:
            args.append(value)
        else:
            # keyword form: a skipped (defaulted, untyped) param must not
            # shift later positional values onto the wrong parameter
            kwargs[name] = value
    return cls(*args, **kwargs)


def probe_class(cls) -> Optional[str]:
    """Round-trip one exception class; returns a problem description or
    None when the class is wire-safe."""
    try:
        inst = _build_instance(cls)
    except Exception as e:  # noqa: BLE001 - any constructor failure is a finding
        return f"could not instantiate from __init__ signature: {e!r}"
    try:
        clone = pickle.loads(pickle.dumps(inst))
    except Exception as e:  # noqa: BLE001
        return f"pickle round-trip raised: {e!r}"
    if type(clone) is not type(inst):
        return (
            f"round-trip changed type: {type(inst).__name__} -> "
            f"{type(clone).__name__}"
        )
    if clone.args != inst.args:
        return f"round-trip lost args: {inst.args!r} -> {clone.args!r}"
    lost = {
        k: v
        for k, v in vars(inst).items()
        if vars(clone).get(k, "<missing>") != v
    }
    if lost:
        return f"round-trip lost fields: {sorted(lost)}"
    return None


class WireTypedErrorsRule(Rule):
    name = "wire-typed-errors"
    allow_token = "wire-error"
    description = (
        "every RayTpuError subclass pickles round-trip preserving args and "
        "fields, and is declared in ray_tpu/exceptions.py"
    )

    def check(self, ctx: LintContext) -> List[Violation]:
        out: List[Violation] = []
        exc_file = ctx.get_file(EXC_REL)
        if exc_file is None:
            return [
                Violation(
                    rule=self.name,
                    path=EXC_REL,
                    line=1,
                    message="exceptions.py not found under lint root",
                )
            ]
        tree_names = _tree_class_names(exc_file)

        # 1) dynamic round-trip probe over the whole tree
        try:
            module = load_exceptions_module(exc_file.path)
        except Exception as e:  # noqa: BLE001
            out.append(
                Violation(
                    rule=self.name,
                    path=EXC_REL,
                    line=1,
                    message=f"could not load exceptions.py for probing: {e!r}",
                )
            )
        else:
            for name, lineno in sorted(tree_names.items()):
                cls = getattr(module, name, None)
                if cls is None or not isinstance(cls, type):
                    continue
                problem = probe_class(cls)
                if problem:
                    out.append(
                        Violation(
                            rule=self.name,
                            path=EXC_REL,
                            line=lineno,
                            message=f"{name}: {problem}",
                        )
                    )

        # 2) tree subclasses declared outside exceptions.py
        for f in ctx.package_files():
            if f.rel == EXC_REL or f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for b in node.bases:
                    base = (
                        b.id
                        if isinstance(b, ast.Name)
                        else b.attr if isinstance(b, ast.Attribute) else None
                    )
                    if base in tree_names:
                        out.append(
                            Violation(
                                rule=self.name,
                                path=f.rel,
                                line=node.lineno,
                                message=(
                                    f"{node.name} subclasses {base} outside "
                                    "ray_tpu/exceptions.py — declare wire "
                                    "errors in the canonical tree so the "
                                    "round-trip probe covers them"
                                ),
                            )
                        )
                        break
        return out
