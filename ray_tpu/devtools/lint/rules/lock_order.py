"""lock-order: build the static lock-acquisition graph across the four
lock-heavy control-plane modules and fail on cycles (the static shadow of a
potential AB/BA deadlock).

Lock identity: ``self.<attr> = threading.Lock()/RLock()`` assignments give
``<File>:<Class>.<attr>`` nodes; module-level ``<name> = threading.Lock()``
gives ``<File>:<name>``.  Acquisition edges come from lexically nested
``with``/``async with`` blocks whose context expressions resolve to known
locks — an outer hold of A around an acquisition of B adds edge A->B.
Calls are not followed (a lock-holding method calling another locking
method is invisible); keep lock scopes lexical and short so the graph
stays meaningful.

Suppression: ``# lint: allow-lock-order -- <reason>`` on the inner ``with``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.engine import LintContext, PyFile, Rule, Violation

LOCK_FILES = (
    "ray_tpu/core/distributed/node_daemon.py",
    "ray_tpu/core/distributed/gcs_server.py",
    "ray_tpu/core/object_store.py",
    "ray_tpu/core/distributed/task_events.py",
)

_LOCK_CTORS = {"Lock", "RLock"}


def _unparse(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _is_lock_ctor(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _LOCK_CTORS:
        return _unparse(func.value).endswith("threading")
    if isinstance(func, ast.Name):
        return func.id in _LOCK_CTORS
    return False


class _FileLocks:
    """Lock declarations found in one file."""

    def __init__(self, f: PyFile):
        self.f = f
        # attr name -> set of class names declaring it as a lock
        self.attr_locks: Dict[str, Set[str]] = {}
        self.module_locks: Set[str] = set()
        tree = f.tree
        if tree is None:
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Assign)
                        and _is_lock_ctor(sub.value)
                    ):
                        for target in sub.targets:
                            if (
                                isinstance(target, ast.Attribute)
                                and isinstance(target.value, ast.Name)
                                and target.value.id == "self"
                            ):
                                self.attr_locks.setdefault(
                                    target.attr, set()
                                ).add(node.name)
        for node in getattr(tree, "body", []):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.module_locks.add(target.id)

    def resolve(self, expr: ast.expr, cls: Optional[str]) -> Optional[str]:
        """Map a with-context expression to a lock id, or None."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.attr_locks
        ):
            owners = self.attr_locks[expr.attr]
            owner = cls if cls in owners else sorted(owners)[0]
            return f"{self.f.rel}:{owner}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.f.rel}:{expr.id}"
        return None


class LockOrderRule(Rule):
    name = "lock-order"
    allow_token = "lock-order"
    description = (
        "the static lock-acquisition graph over node_daemon/gcs_server/"
        "object_store/task_events must be acyclic"
    )

    def check(self, ctx: LintContext) -> List[Violation]:
        # edge -> (path, line) of the inner acquisition that created it
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for rel in LOCK_FILES:
            f = ctx.get_file(rel)
            if f is None or f.tree is None:
                continue
            locks = _FileLocks(f)
            for node in ast.walk(f.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls = _enclosing_class(f.tree, node)
                    self._walk(node.body, [], locks, cls, edges)

        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())

        out: List[Violation] = []
        for cycle in _find_cycles(graph):
            # attribute the violation to the edge that closes the cycle
            closing = (cycle[-1], cycle[0])
            path, line = edges.get(closing, edges.get((cycle[0], cycle[1]), ("", 1)))
            pretty = " -> ".join(cycle + [cycle[0]])
            out.append(
                Violation(
                    rule=self.name,
                    path=path or LOCK_FILES[0],
                    line=line,
                    message=(
                        f"lock-order cycle (potential AB/BA deadlock): {pretty}"
                    ),
                )
            )
        return out

    def _walk(
        self,
        body: List[ast.stmt],
        held: List[str],
        locks: _FileLocks,
        cls: Optional[str],
        edges: Dict[Tuple[str, str], Tuple[str, int]],
    ) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in node.items:
                    lock_id = locks.resolve(item.context_expr, cls)
                    if lock_id is not None:
                        for outer in held + acquired:
                            if outer != lock_id:
                                edges.setdefault(
                                    (outer, lock_id), (locks.f.rel, node.lineno)
                                )
                        acquired.append(lock_id)
                self._walk(node.body, held + acquired, locks, cls, edges)
                continue
            for field_name in getattr(node, "_fields", ()):
                value = getattr(node, field_name, None)
                if (
                    isinstance(value, list)
                    and value
                    and isinstance(value[0], (ast.stmt, ast.excepthandler))
                ):
                    self._walk(value, held, locks, cls, edges)


def _enclosing_class(tree: ast.AST, fn: ast.AST) -> Optional[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                if child is fn:
                    return node.name
    return None


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Deterministic simple-cycle detection (DFS back-edges); each cycle is
    reported once, rotated to start at its smallest node."""
    seen_cycles: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []

    def dfs(node: str, stack: List[str], on_stack: Set[str], visited: Set[str]):
        visited.add(node)
        stack.append(node)
        on_stack.add(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                i = stack.index(nxt)
                cyc = stack[i:]
                j = cyc.index(min(cyc))
                key = tuple(cyc[j:] + cyc[:j])
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(list(key))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return cycles
