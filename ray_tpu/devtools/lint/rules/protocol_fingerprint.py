"""protocol-fingerprint: the frame-layout constants and codec markers in
``wire.py`` / ``rpc.py`` / ``protocol.py`` are hashed and compared against a
checked-in fingerprint keyed by ``PROTOCOL_VERSION``.  Editing the layout
without bumping the version fails the lint; bumping the version without
recording the new fingerprint also fails (run
``ray-tpu lint --update-fingerprint`` after auditing the change).

What goes into the hash (extracted statically, so the rule works on fixture
trees and never imports the modules):

- ``wire.py``: ``CODEC_*`` markers, ``_T_*`` typed-codec tags, the ``_I64``/
  ``_F64``/``_U32`` struct formats, and ``Raw.__slots__``
- ``rpc.py``: frame-type constants (``REQ``..``CANCEL``), ``MAX_FRAME``,
  ``_POST_LEN``, and the ``_HEADER`` struct format
- ``protocol.py``: ``RefMarker.__slots__``, the ``TaskResult`` field list,
  and the key set of the dict built by ``make_task_spec``

``PROTOCOL_VERSION`` itself is deliberately excluded from the hash: the
fingerprint maps *version -> layout*, so a layout change under an unchanged
version is exactly the failure mode being caught.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ray_tpu.devtools.lint.engine import LintContext, PyFile, Rule, Violation

WIRE_REL = "ray_tpu/core/distributed/wire.py"
RPC_REL = "ray_tpu/core/distributed/rpc.py"
PROTO_REL = "ray_tpu/core/distributed/protocol.py"
FINGERPRINT_REL = "ray_tpu/devtools/lint/protocol_fingerprint.json"

_WIRE_NAME_RE = re.compile(r"^(_T_[A-Z0-9_]+|CODEC_[A-Z0-9_]+|_I64|_F64|_U32)$")
_RPC_NAMES = {
    "REQ", "RES", "STREAM_REQ", "STREAM_ITEM", "STREAM_END", "CANCEL",
    "MAX_FRAME", "_POST_LEN", "_HEADER",
}


def _const_repr(node: ast.expr) -> str:
    """Deterministic string for a constant expression.

    ``struct.Struct("<q")`` renders as ``Struct('<q')`` so the *format* is
    what is fingerprinted; arithmetic like ``512 * 1024 * 1024`` is folded;
    anything else falls back to the (deterministic) AST dump.
    """
    if isinstance(node, ast.Call):
        func = node.func
        fname = func.attr if isinstance(func, ast.Attribute) else getattr(func, "id", "")
        if fname == "Struct" and node.args and isinstance(node.args[0], ast.Constant):
            return f"Struct({node.args[0].value!r})"
    try:
        value = eval(  # noqa: S307 - constant folding only, no names/builtins
            compile(ast.Expression(node), "<fingerprint>", "eval"),
            {"__builtins__": {}},
        )
        return repr(value)
    except Exception:
        return ast.dump(node)


def _module_constants(pyfile: PyFile, want) -> Dict[str, str]:
    tree = pyfile.tree
    if tree is None:
        return {}
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and want(target.id):
                out[target.id] = _const_repr(node.value)
    return out


def _class_slots(pyfile: PyFile, class_name: str) -> Optional[str]:
    tree = pyfile.tree
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "__slots__"
                ):
                    return _const_repr(stmt.value)
    return None


def _namedtuple_fields(pyfile: PyFile, class_name: str) -> Optional[str]:
    tree = pyfile.tree
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            fields = [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
            return repr(fields)
    return None


def _task_spec_keys(pyfile: PyFile) -> Optional[str]:
    """Key set of the dict literal returned by make_task_spec."""
    tree = pyfile.tree
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "make_task_spec":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    keys = sorted(
                        k.value
                        for k in sub.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    )
                    return repr(keys)
    return None


def read_protocol_version(wire_file: PyFile) -> Optional[int]:
    tree = wire_file.tree
    if tree is None:
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "PROTOCOL_VERSION":
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, int
                ):
                    return node.value.value
    return None


def compute_fingerprint(ctx: LintContext) -> Tuple[Optional[str], List[str]]:
    """Returns (sha256 hex digest, list of problems). The digest is None when
    any of the three layout files is missing or unparsable."""
    problems: List[str] = []
    layout: Dict[str, Dict[str, str]] = {}

    wire = ctx.get_file(WIRE_REL)
    if wire is None or wire.tree is None:
        problems.append(f"{WIRE_REL} missing or unparsable")
    else:
        consts = _module_constants(wire, lambda n: bool(_WIRE_NAME_RE.match(n)))
        slots = _class_slots(wire, "Raw")
        if slots is not None:
            consts["Raw.__slots__"] = slots
        layout[WIRE_REL] = consts

    rpc = ctx.get_file(RPC_REL)
    if rpc is None or rpc.tree is None:
        problems.append(f"{RPC_REL} missing or unparsable")
    else:
        layout[RPC_REL] = _module_constants(rpc, lambda n: n in _RPC_NAMES)

    proto = ctx.get_file(PROTO_REL)
    if proto is None or proto.tree is None:
        problems.append(f"{PROTO_REL} missing or unparsable")
    else:
        consts = {}
        slots = _class_slots(proto, "RefMarker")
        if slots is not None:
            consts["RefMarker.__slots__"] = slots
        fields = _namedtuple_fields(proto, "TaskResult")
        if fields is not None:
            consts["TaskResult.fields"] = fields
        keys = _task_spec_keys(proto)
        if keys is not None:
            consts["make_task_spec.keys"] = keys
        layout[PROTO_REL] = consts

    if problems:
        return None, problems
    canonical = json.dumps(layout, sort_keys=True)
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest(), []


def fingerprint_path(root: Path) -> Path:
    return Path(root) / FINGERPRINT_REL


def load_recorded(root: Path) -> Dict[str, str]:
    path = fingerprint_path(root)
    if not path.is_file():
        return {}
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    versions = doc.get("versions", doc)
    return {str(k): str(v) for k, v in versions.items() if isinstance(v, str)}


def update_fingerprint(root: Path) -> Tuple[Optional[int], Optional[str]]:
    """Record the current layout hash under the current PROTOCOL_VERSION.
    Returns (version, digest); raises on missing/unparsable layout files."""
    ctx = LintContext(root)
    wire = ctx.get_file(WIRE_REL)
    if wire is None:
        raise FileNotFoundError(f"{WIRE_REL} not found under {root}")
    version = read_protocol_version(wire)
    if version is None:
        raise ValueError(f"PROTOCOL_VERSION not found in {WIRE_REL}")
    digest, problems = compute_fingerprint(ctx)
    if digest is None:
        raise ValueError("; ".join(problems))
    recorded = load_recorded(ctx.root)
    recorded[str(version)] = digest
    path = fingerprint_path(ctx.root)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        json.dumps(
            {"schema": 1, "versions": dict(sorted(recorded.items(), key=lambda kv: int(kv[0])))},
            indent=2,
        )
        + "\n",
        encoding="utf-8",
    )
    return version, digest


class ProtocolFingerprintRule(Rule):
    name = "protocol-fingerprint"
    allow_token = "fingerprint"
    description = (
        "frame-layout constants in wire.py/rpc.py/protocol.py must match the "
        "fingerprint recorded for the current PROTOCOL_VERSION"
    )

    def check(self, ctx: LintContext) -> List[Violation]:
        wire = ctx.get_file(WIRE_REL)
        if wire is None:
            return [
                Violation(
                    rule=self.name,
                    path=WIRE_REL,
                    line=1,
                    message="wire.py not found under lint root",
                )
            ]
        version = read_protocol_version(wire)
        if version is None:
            return [
                Violation(
                    rule=self.name,
                    path=WIRE_REL,
                    line=1,
                    message="PROTOCOL_VERSION literal not found in wire.py",
                )
            ]
        digest, problems = compute_fingerprint(ctx)
        if digest is None:
            return [
                Violation(rule=self.name, path=WIRE_REL, line=1, message=p)
                for p in problems
            ]
        recorded = load_recorded(ctx.root)
        expected = recorded.get(str(version))
        if expected is None:
            return [
                Violation(
                    rule=self.name,
                    path=FINGERPRINT_REL,
                    line=1,
                    message=(
                        f"no fingerprint recorded for PROTOCOL_VERSION "
                        f"{version} — audit the frame layout, then run "
                        "'ray-tpu lint --update-fingerprint'"
                    ),
                )
            ]
        if expected != digest:
            return [
                Violation(
                    rule=self.name,
                    path=WIRE_REL,
                    line=1,
                    message=(
                        f"frame-layout constants changed but PROTOCOL_VERSION "
                        f"is still {version} (recorded {expected[:12]}…, "
                        f"current {digest[:12]}…) — bump PROTOCOL_VERSION in "
                        "wire.py and run 'ray-tpu lint --update-fingerprint'"
                    ),
                )
            ]
        return []
