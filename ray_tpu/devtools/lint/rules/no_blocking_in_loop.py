"""no-blocking-in-loop: the daemon/GCS event loops in ``core/distributed/``
must never block.  Flags, inside ``async def`` bodies and inside lambdas
dispatched onto a loop via ``call_soon`` / ``call_soon_threadsafe`` /
``call_later`` (the EventLoopThread pattern):

- ``time.sleep(...)``                  -> use ``await asyncio.sleep(...)``
- ``ray_tpu.get(...)`` / ``ray.get``   -> await the ref or use an executor
- ``<fut>.result()``                   -> await it (``asyncio.wrap_future``)
- blocking socket calls (``connect`` / ``accept`` / ``recv*`` / ``sendall``
  on a socket-ish receiver, ``socket.create_connection``)

Recognised-safe idiom (not flagged): calling ``.result()`` on members of a
completed-task set from ``done, _ = await asyncio.wait(...)`` — those
futures are already resolved, so ``.result()`` cannot block.

Nested *sync* ``def`` bodies are skipped (they run wherever they are
called, e.g. executor threads or done-callbacks on resolved futures);
nested ``async def`` are scanned as their own scope.

A second scope guards the decode-on-rails hot loops (serve's compiled
streaming path): the per-frame bodies of the replica's rails pump, the
handle's channel pull, and the local ring's read/publish paths must stay
RPC-free — a per-token actor round trip is exactly the overhead rails
exist to remove.  Flagged there: ``ray_tpu.get``/``ray.get``, actor
``.remote(...)`` submissions, and daemon/GCS ``.call(...)``.  Exception
handlers are NOT scanned: idle-slice liveness probes and error recovery
are off the hot path by definition, which is where such calls belong.

Suppression: ``# lint: allow-blocking -- <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu.devtools.lint.engine import LintContext, PyFile, Rule, Violation

SCOPE_PREFIX = "ray_tpu/core/distributed/"

_SOCKET_METHODS = {"recv", "recv_into", "recvfrom", "accept", "connect", "sendall"}
_DISPATCH_METHODS = {"call_soon", "call_soon_threadsafe", "call_later", "call_at"}

# file -> dotted qualnames whose bodies are rails hot loops.  The channel
# entries pin the local ring to pure mmap+poll (RemoteChannelWriter, the
# cross-host endpoint, is deliberately absent: its job IS the daemon RPC).
RAILS_HOT_LOOPS: Dict[str, Set[str]] = {
    "ray_tpu/serve/replica.py": {"Replica._rails_pump"},
    "ray_tpu/serve/handle.py": {"StreamingResponse._rails_next"},
    "ray_tpu/experimental/channel.py": {"Channel.read", "Channel.write",
                                        "Channel.write_bytes"},
}

# file -> dotted qualnames on the flight-recorder journal write path.
# These run ON the GCS event loop for every journalled state transition
# (node death during a storm, drain fan-out, PG repair), so the durable
# append — PersistentStore.put fsyncs under a lock — must leave the loop
# via run_in_executor.  Flagged here: blocking calls (same set as the
# async-body scan) plus DIRECT store writes (.put/.delete on a store-ish
# receiver).  Exception handlers are exempt (the loop-less sync fallback
# for journal writes issued before/after the GCS loop runs lives there).
JOURNAL_WRITE_PATHS: Dict[str, Set[str]] = {
    "ray_tpu/core/distributed/gcs_server.py": {
        "FlightRecorder.record",
        "FlightRecorder._schedule_persist",
    },
}


def _unparse(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _sleep_aliases(tree: ast.AST) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    aliases.add(alias.asname or alias.name)
    return aliases


def _is_wait_call(node: ast.expr) -> bool:
    """True for ``await asyncio.wait(...)`` values."""
    if isinstance(node, ast.Await):
        node = node.value
    if isinstance(node, ast.Call):
        text = _unparse(node.func)
        return text.endswith("asyncio.wait") or text == "wait"
    return False


def _collect_safe_result_names(body: List[ast.stmt]) -> Set[str]:
    """Names that hold members of an ``asyncio.wait`` done-set within this
    (single) function body: the done-set names themselves and the loop vars
    iterating over them."""
    done_sets: Set[str] = set()
    safe: Set[str] = set()
    for node in _walk_same_scope(body):
        if isinstance(node, ast.Assign) and _is_wait_call(node.value):
            for target in node.targets:
                if isinstance(target, ast.Tuple) and target.elts:
                    first = target.elts[0]
                    if isinstance(first, ast.Name):
                        done_sets.add(first.id)
                elif isinstance(target, ast.Name):
                    done_sets.add(target.id)
    for node in _walk_same_scope(body):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            iter_text = _unparse(node.iter)
            if isinstance(node.iter, ast.Name) and node.iter.id in done_sets:
                safe.add(node.target.id)
            elif any(iter_text.startswith(d + ".") for d in done_sets):
                safe.add(node.target.id)
    return safe | done_sets


def _walk_same_scope(body: List[ast.stmt]):
    """Yield all nodes in *body* without descending into nested function or
    class definitions (lambdas ARE descended into: they run in this scope's
    thread)."""
    _defs = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    stack: List[ast.AST] = [n for n in body if not isinstance(n, _defs)]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            stack.append(child)


def _walk_hot_path(body: List[ast.stmt]):
    """Yield nodes on a rails hot loop's per-frame path: skip nested
    defs/classes (they run elsewhere) AND except handlers (idle-slice
    probes / error recovery run off the hot path)."""
    _defs = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    stack: List[ast.AST] = [n for n in body if not isinstance(n, _defs)]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ast.Try):
            children = list(node.body) + list(node.orelse) + list(node.finalbody)
        else:
            children = list(ast.iter_child_nodes(node))
        for child in children:
            if isinstance(child, (*_defs, ast.ExceptHandler)):
                continue
            stack.append(child)


def _rpc_message(call: ast.Call) -> Optional[str]:
    """RPC-shaped calls banned on a rails per-frame path."""
    func = call.func
    text = _unparse(func)
    if text in ("ray_tpu.get", "ray.get"):
        return (
            "ray_tpu.get() on a rails hot loop — per-frame round trips "
            "defeat the compiled path; move it to an idle-slice handler"
        )
    if isinstance(func, ast.Attribute):
        if func.attr == "remote":
            return (
                f"actor RPC '{_unparse(func)}(...)' on a rails hot loop — "
                "frames must ride the channel plane, not per-token actor "
                "calls"
            )
        recv = _unparse(func.value).lower()
        if func.attr == "call" and any(
            k in recv for k in ("rpc", "daemon", "client", "gcs")
        ):
            return (
                f"daemon/GCS RPC '{_unparse(func)}(...)' on a rails hot "
                "loop — the local ring must stay pure mmap+poll"
            )
    return None


def _blocking_message(
    call: ast.Call, sleep_aliases: Set[str], safe_results: Set[str]
) -> Optional[str]:
    func = call.func
    text = _unparse(func)
    if text == "time.sleep" or (
        isinstance(func, ast.Name) and func.id in sleep_aliases
    ):
        return "time.sleep() blocks the event loop — use 'await asyncio.sleep(...)'"
    if text in ("ray_tpu.get", "ray.get"):
        return (
            "blocking ray_tpu.get() on the event loop — await the ref or "
            "resolve it in an executor"
        )
    if text.endswith("socket.create_connection"):
        return (
            "socket.create_connection() blocks the event loop — use "
            "asyncio.open_connection()"
        )
    if isinstance(func, ast.Attribute):
        recv = func.value
        if func.attr == "result":
            if isinstance(recv, ast.Name) and recv.id in safe_results:
                return None
            return (
                "Future.result() blocks the event loop — await the future "
                "(asyncio.wrap_future for concurrent futures)"
            )
        if func.attr in _SOCKET_METHODS and "sock" in _unparse(recv).lower():
            return (
                f"blocking socket .{func.attr}() on the event loop — use the "
                "asyncio stream/protocol APIs"
            )
    return None


def _store_write_message(call: ast.Call) -> Optional[str]:
    """Direct durable-store writes banned on the journal write path:
    PersistentStore.put/.delete fsync under a lock, so every journalled
    transition would stall the GCS loop for a disk round trip."""
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in ("put", "delete"):
        return None
    recv = _unparse(func.value).lower()
    if "store" in recv:
        return (
            f"durable store .{func.attr}() on the flight-recorder write "
            "path — PersistentStore fsyncs under a lock; ship the entry "
            "through loop.run_in_executor instead"
        )
    return None


class NoBlockingInLoopRule(Rule):
    name = "no-blocking-in-loop"
    allow_token = "blocking"
    description = (
        "no time.sleep / blocking sockets / Future.result / ray_tpu.get "
        "inside async bodies or loop-dispatched callbacks in "
        "core/distributed/; no RPC round trips on the decode-on-rails "
        "per-frame paths (serve rails pump, handle channel pull, local "
        "ring read/publish); no blocking or direct durable-store writes "
        "on the flight-recorder journal path"
    )

    def check(self, ctx: LintContext) -> List[Violation]:
        out: List[Violation] = []
        for f in ctx.package_files():
            if f.tree is not None and f.rel in RAILS_HOT_LOOPS:
                self._scan_rails(f, RAILS_HOT_LOOPS[f.rel], out)
            if f.tree is not None and f.rel in JOURNAL_WRITE_PATHS:
                self._scan_journal(f, JOURNAL_WRITE_PATHS[f.rel], out)
            if not f.rel.startswith(SCOPE_PREFIX) or f.tree is None:
                continue
            sleep_aliases = _sleep_aliases(f.tree)

            # async function bodies
            for node in ast.walk(f.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    self._scan_body(f, node.body, sleep_aliases, out)

            # lambdas handed to loop.call_soon/_threadsafe/call_later from
            # any (sync or async) context — EventLoopThread dispatch sites
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in _DISPATCH_METHODS
                ):
                    for arg in node.args:
                        if isinstance(arg, ast.Lambda):
                            self._scan_expr(f, arg.body, sleep_aliases, set(), out)
        return out

    def _scan_rails(
        self, f: PyFile, qualnames: Set[str], out: List[Violation]
    ) -> None:
        """Scan the named hot-loop bodies for RPC-shaped calls.  A listed
        qualname that no longer resolves is itself a violation, so the
        registry can't silently rot as functions move."""
        found: Set[str] = set()
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qn = f"{cls.name}.{fn.name}"
                if qn not in qualnames:
                    continue
                found.add(qn)
                for node in _walk_hot_path(fn.body):
                    if isinstance(node, ast.Call):
                        msg = _rpc_message(node)
                        if msg:
                            out.append(
                                Violation(
                                    rule=self.name,
                                    path=f.rel,
                                    line=node.lineno,
                                    message=msg,
                                )
                            )
        for missing in sorted(qualnames - found):
            out.append(
                Violation(
                    rule=self.name,
                    path=f.rel,
                    line=1,
                    message=(
                        f"rails hot-loop registry names {missing!r} but no "
                        "such method exists — update RAILS_HOT_LOOPS"
                    ),
                )
            )

    def _scan_journal(
        self, f: PyFile, qualnames: Set[str], out: List[Violation]
    ) -> None:
        """Scan the flight-recorder write-path bodies for blocking calls
        and direct durable-store writes.  Like the rails registry, a
        listed qualname that no longer resolves is itself a violation."""
        sleep_aliases = _sleep_aliases(f.tree)
        found: Set[str] = set()
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qn = f"{cls.name}.{fn.name}"
                if qn not in qualnames:
                    continue
                found.add(qn)
                for node in _walk_hot_path(fn.body):
                    if not isinstance(node, ast.Call):
                        continue
                    msg = _blocking_message(
                        node, sleep_aliases, set()
                    ) or _store_write_message(node)
                    if msg:
                        out.append(
                            Violation(
                                rule=self.name,
                                path=f.rel,
                                line=node.lineno,
                                message=msg,
                            )
                        )
        for missing in sorted(qualnames - found):
            out.append(
                Violation(
                    rule=self.name,
                    path=f.rel,
                    line=1,
                    message=(
                        f"journal write-path registry names {missing!r} but "
                        "no such method exists — update JOURNAL_WRITE_PATHS"
                    ),
                )
            )

    def _scan_body(
        self,
        f: PyFile,
        body: List[ast.stmt],
        sleep_aliases: Set[str],
        out: List[Violation],
    ) -> None:
        safe_results = _collect_safe_result_names(body)
        for node in _walk_same_scope(body):
            if isinstance(node, ast.Call):
                msg = _blocking_message(node, sleep_aliases, safe_results)
                if msg:
                    out.append(
                        Violation(
                            rule=self.name, path=f.rel, line=node.lineno, message=msg
                        )
                    )

    def _scan_expr(
        self,
        f: PyFile,
        expr: ast.expr,
        sleep_aliases: Set[str],
        safe_results: Set[str],
        out: List[Violation],
    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                msg = _blocking_message(node, sleep_aliases, safe_results)
                if msg:
                    out.append(
                        Violation(
                            rule=self.name, path=f.rel, line=node.lineno, message=msg
                        )
                    )
