"""The rule catalogue. ``build_rules()`` is the single discovery point used
by the engine, the CLI, and the tests."""

from typing import List

from ray_tpu.devtools.lint.engine import Rule
from ray_tpu.devtools.lint.rules.knob_registry import KnobRegistryRule
from ray_tpu.devtools.lint.rules.wire_typed_errors import WireTypedErrorsRule
from ray_tpu.devtools.lint.rules.protocol_fingerprint import ProtocolFingerprintRule
from ray_tpu.devtools.lint.rules.no_blocking_in_loop import NoBlockingInLoopRule
from ray_tpu.devtools.lint.rules.lock_order import LockOrderRule
from ray_tpu.devtools.lint.rules.reserved_kwargs import ReservedKwargsRule

__all__ = ["build_rules"]


def build_rules() -> List[Rule]:
    return [
        KnobRegistryRule(),
        WireTypedErrorsRule(),
        ProtocolFingerprintRule(),
        NoBlockingInLoopRule(),
        LockOrderRule(),
        ReservedKwargsRule(),
    ]
