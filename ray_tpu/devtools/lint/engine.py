"""Rule engine for the ray-tpu invariant lint suite.

The suite is AST-based: every rule receives a :class:`LintContext` that lazily
parses the python files under a root directory and exposes the allowlist
comments found in them.  Rules return :class:`Violation` records; the engine
applies allowlist suppression centrally and adds its own hygiene checks
(allow entries must name a known rule and must carry a reason).

Allowlist grammar (one comment, same line as the violation or the line
directly above it)::

    # lint: allow-<token> -- <reason>

where ``<token>`` is either a rule's short allow token (e.g. ``blocking``)
or the full rule name (e.g. ``no-blocking-in-loop``).  A missing reason is
itself a violation, so the suite can guarantee "zero allowlist entries
lacking a reason".
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Violation",
    "AllowEntry",
    "PyFile",
    "LintContext",
    "Rule",
    "run_lint",
    "all_rules",
    "rule_names",
    "to_json",
    "render_text",
    "default_root",
]

JSON_SCHEMA_VERSION = 1

# Directories never scanned, wherever they appear under the root.
_SKIP_DIRS = {
    "__pycache__", ".git", ".wt-seed", ".claude", "node_modules",
    ".pytest_cache", "build", "dist",
}


@dataclass(frozen=True)
class Violation:
    """One rule finding, attributed to a file/line relative to the lint root."""

    rule: str
    path: str
    line: int
    message: str

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass(frozen=True)
class AllowEntry:
    """A parsed ``# lint: allow-<token> -- <reason>`` comment.

    A *standalone* comment (nothing but whitespace before it) covers the
    next line; a trailing comment covers its own line.
    """

    token: str
    reason: str
    path: str
    line: int
    standalone: bool = False

    def covers(self, line: int) -> bool:
        return line == (self.line + 1 if self.standalone else self.line)


class PyFile:
    """A lazily parsed python source file."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self._source: Optional[str] = None
        self._tree: Optional[ast.AST] = None
        self._tree_error: Optional[SyntaxError] = None
        self._allows: Optional[List[AllowEntry]] = None

    @property
    def source(self) -> str:
        if self._source is None:
            self._source = self.path.read_text(encoding="utf-8", errors="replace")
        return self._source

    @property
    def tree(self) -> Optional[ast.AST]:
        """Parsed module, or ``None`` when the file does not parse."""
        if self._tree is None and self._tree_error is None:
            try:
                self._tree = ast.parse(self.source, filename=self.rel)
            except SyntaxError as e:
                self._tree_error = e
        return self._tree

    @property
    def allows(self) -> List[AllowEntry]:
        if self._allows is None:
            self._allows = parse_allow_comments(self.source, self.rel)
        return self._allows


_ALLOW_RE = re.compile(
    r"lint:\s*allow-(?P<token>[A-Za-z0-9_-]+)"
    r"(?:\s+--\s*(?P<reason>.*?))?\s*$"
)


def parse_allow_comments(source: str, rel: str) -> List[AllowEntry]:
    """Extract allowlist entries from *real* comments (tokenize-based, so
    examples inside docstrings are ignored)."""
    if "lint:" not in source:
        return []
    entries: List[AllowEntry] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ALLOW_RE.search(tok.string)
            if m:
                entries.append(
                    AllowEntry(
                        token=m.group("token"),
                        reason=(m.group("reason") or "").strip(),
                        path=rel,
                        line=tok.start[0],
                        standalone=not tok.line[: tok.start[1]].strip(),
                    )
                )
    except tokenize.TokenError:
        pass
    return entries


class LintContext:
    """Shared state handed to every rule: the root, parsed files, allowlist."""

    def __init__(self, root: Path):
        self.root = Path(root).resolve()
        self._files: Optional[Dict[str, PyFile]] = None

    # -- file access -----------------------------------------------------

    def _scan(self) -> Dict[str, PyFile]:
        if self._files is None:
            files: Dict[str, PyFile] = {}
            for path in sorted(self.root.rglob("*.py")):
                rel_parts = path.relative_to(self.root).parts
                if any(p in _SKIP_DIRS for p in rel_parts):
                    continue
                rel = "/".join(rel_parts)
                files[rel] = PyFile(path, rel)
            self._files = files
        return self._files

    def py_files(self, prefix: str = "") -> List[PyFile]:
        """All python files whose root-relative path starts with *prefix*."""
        return [f for rel, f in self._scan().items() if rel.startswith(prefix)]

    def package_files(self) -> List[PyFile]:
        """Files under ``<root>/ray_tpu`` when it exists, else the whole root.

        Fixture trees mirror the real layout, so rules can address files by
        the same relative paths in both worlds.
        """
        if (self.root / "ray_tpu").is_dir():
            return self.py_files("ray_tpu/")
        return self.py_files("")

    def get_file(self, rel: str) -> Optional[PyFile]:
        return self._scan().get(rel)

    # -- allowlist -------------------------------------------------------

    def allow_entries(self) -> List[AllowEntry]:
        entries: List[AllowEntry] = []
        for f in self.package_files():
            entries.extend(f.allows)
        # examples/ is scanned by reserved-kwargs, so honour allows there too
        if (self.root / "ray_tpu").is_dir():
            for f in self.py_files("examples/"):
                entries.extend(f.allows)
        return entries

    def is_allowed(self, rel: str, line: int, tokens: Sequence[str]) -> bool:
        """True when an allow comment with one of *tokens* covers *line*
        (trailing comment on the line itself, or a standalone comment on
        the line directly above)."""
        f = self._scan().get(rel)
        if f is None:
            return False
        return any(
            entry.token in tokens and entry.covers(line) for entry in f.allows
        )


class Rule:
    """Base class: subclasses set ``name``/``allow_token`` and implement
    :meth:`check`."""

    name: str = ""
    allow_token: str = ""
    description: str = ""

    def check(self, ctx: LintContext) -> List[Violation]:
        raise NotImplementedError

    def allow_tokens(self) -> Tuple[str, ...]:
        return (self.allow_token, self.name) if self.allow_token else (self.name,)


def all_rules() -> List[Rule]:
    """Instantiate the full rule set (import deferred to avoid cycles)."""
    from ray_tpu.devtools.lint.rules import build_rules

    return build_rules()


def rule_names() -> List[str]:
    return [r.name for r in all_rules()]


def _allowlist_hygiene(ctx: LintContext, rules: Sequence[Rule]) -> List[Violation]:
    known: Dict[str, str] = {}
    for r in rules:
        for tok in r.allow_tokens():
            known[tok] = r.name
    out: List[Violation] = []
    for entry in ctx.allow_entries():
        if entry.token not in known:
            out.append(
                Violation(
                    rule="allowlist",
                    path=entry.path,
                    line=entry.line,
                    message=(
                        f"allow entry names unknown rule token "
                        f"'{entry.token}' (known: {', '.join(sorted(known))})"
                    ),
                )
            )
        elif not entry.reason:
            out.append(
                Violation(
                    rule="allowlist",
                    path=entry.path,
                    line=entry.line,
                    message=(
                        f"allow entry for '{entry.token}' has no reason — "
                        "write '# lint: allow-%s -- <why this is safe>'"
                        % entry.token
                    ),
                )
            )
    return out


def run_lint(
    root: Optional[Path] = None,
    rules: Optional[Sequence[str]] = None,
) -> Tuple[List[Violation], List[Rule]]:
    """Run the suite. Returns ``(violations, rules_run)``.

    *rules* filters by rule name; unknown names raise :class:`ValueError`.
    Allowlist hygiene always runs (it is what guarantees every suppression
    carries a reason).
    """
    ctx = LintContext(root or default_root())
    available = all_rules()
    if rules:
        by_name = {r.name: r for r in available}
        unknown = [n for n in rules if n not in by_name]
        if unknown:
            raise ValueError(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(available: {', '.join(sorted(by_name))})"
            )
        selected = [by_name[n] for n in rules]
    else:
        selected = available

    violations: List[Violation] = []
    for rule in selected:
        tokens = rule.allow_tokens()
        for v in rule.check(ctx):
            if ctx.is_allowed(v.path, v.line, tokens):
                continue
            violations.append(v)
    # hygiene checks run against the full token vocabulary so an allow for a
    # deselected rule is still recognised
    violations.extend(_allowlist_hygiene(ctx, available))
    violations = sorted(set(violations), key=lambda v: (v.path, v.line, v.rule, v.message))
    return violations, selected


def default_root() -> Path:
    """Repo root inferred from this file's location (…/ray_tpu/devtools/lint)."""
    return Path(__file__).resolve().parents[3]


def to_json(
    root: Path, violations: Sequence[Violation], rules: Sequence[Rule]
) -> str:
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    doc = {
        "schema": JSON_SCHEMA_VERSION,
        "root": str(root),
        "rules": [r.name for r in rules],
        "ok": not violations,
        "counts": counts,
        "violations": [v.as_dict() for v in violations],
    }
    return json.dumps(doc, indent=2, sort_keys=False)


def render_text(
    root: Path, violations: Sequence[Violation], rules: Sequence[Rule]
) -> str:
    lines = []
    for v in violations:
        lines.append(f"{v.path}:{v.line}: [{v.rule}] {v.message}")
    n = len(violations)
    lines.append(
        f"ray-tpu lint: {n} violation{'s' if n != 1 else ''} "
        f"({len(rules)} rule{'s' if len(rules) != 1 else ''} checked) in {root}"
    )
    return "\n".join(lines)
