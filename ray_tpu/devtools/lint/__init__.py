"""AST-based invariant lint suite (`ray-tpu lint`).

Public surface:

- :func:`run_lint` — run the suite over a root, returns (violations, rules)
- :func:`all_rules` / :func:`rule_names` — rule discovery
- :func:`to_json` / :func:`render_text` — output formatting
"""

from ray_tpu.devtools.lint.engine import (  # noqa: F401
    AllowEntry,
    LintContext,
    PyFile,
    Rule,
    Violation,
    all_rules,
    default_root,
    parse_allow_comments,
    render_text,
    rule_names,
    run_lint,
    to_json,
)
