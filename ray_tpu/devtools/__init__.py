"""Developer tooling that ships with the package but is not part of the
runtime: the invariant lint suite lives under :mod:`ray_tpu.devtools.lint`.
"""
