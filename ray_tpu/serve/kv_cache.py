"""KV block allocator: refcounted block pool with prefix sharing + COW.

The host half of the paged KV cache (device half: PagedKVCache in
models/decoding.py).  The allocator owns which pool blocks belong to which
request, shares blocks between requests with a common prompt prefix
(refcounted, vLLM automatic-prefix-caching at block granularity), and
duplicates a shared partial block before a new owner appends into it
(copy-on-write — the engine runs the device-side copy_block, then swaps
the table entry the allocator hands back).

Block 0 is the reserved NULL block: never allocated, every unused table
entry points at it, so the compiled gather/scatter is always in-bounds.

The pool's bytes are carved out of the node's shared-memory object store
through the create-then-fill seam (ObjectStore.create_arena): the arena
reservation makes KV pressure visible to the store accounting/syncer
plane, and releasing it returns the store to quiescence — the leak-guard
test asserts used/num_objects return to baseline.  Engines running
without a store (standalone, unit tests) skip the arena.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple


def prefix_digest(tokens) -> str:
    """Stable cluster-wide digest of a cumulative token prefix.  Keyed
    on the raw token values (not positions), so two replicas that
    prefilled the same prompt prefix — or a prefill actor that shipped
    it — derive the SAME digest and the prefix registry can match them
    without ever moving token lists through the GCS."""
    h = hashlib.sha1()
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()[:16]


class KVBlockAllocator:
    """Free-list + refcounts + prefix map over ``num_blocks`` pool blocks
    of ``block_size`` tokens each (block 0 reserved).

    Prefix map: key = tuple of ALL prompt tokens up to and including a
    block's chunk (cumulative keys make lookups exact, not positional).
    Freed blocks that carry a prefix key become "cached-free": refcount
    0, contents intact, LRU-evictable when the free list runs dry.  A
    lookup hit on a cached-free block revives it (refcount 1) without
    re-prefilling — that is the block-reuse counter the acceptance
    criterion asserts on.
    """

    def __init__(self, num_blocks: int, block_size: int, *,
                 store: Any = None, bytes_per_block: int = 0,
                 prefix_sharing: bool = True, arena_name: str = "kv-pool"):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_sharing = prefix_sharing
        self._lock = threading.Lock()
        self._free: deque = deque(range(1, num_blocks))
        self._ref = [0] * num_blocks
        # prefix key -> block id; insertion order over CACHED (refcount
        # 0) entries is the eviction LRU.
        self._by_key: Dict[tuple, int] = {}
        self._key_of: Dict[int, tuple] = {}
        # key -> cluster-stable digest (computed once at registration;
        # the gauge loop publishes these to the cluster prefix registry).
        self._digest_of: Dict[tuple, str] = {}
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        # full-prompt key -> metadata (last-token logits) so a whole-
        # prompt hit can sample its first token without any forward.
        self._meta: Dict[tuple, Any] = {}
        self.stats = {"reuse_hits": 0, "reuse_misses": 0, "cow_copies": 0,
                      "evictions": 0, "alloc_failures": 0}
        self._arena = None
        self.arena_bytes = 0
        if store is not None and bytes_per_block > 0:
            self._reserve_arena(store, bytes_per_block, arena_name)

    # -- shm arena ------------------------------------------------------
    def _reserve_arena(self, store, bytes_per_block: int,
                       arena_name: str) -> None:
        from ray_tpu.core.ids import ObjectID

        oid = ObjectID.from_random()
        size = self.num_blocks * bytes_per_block
        try:
            self._arena = store.create_arena(oid, size)
            self.arena_bytes = size
        except Exception:  # noqa: BLE001 — pool works unreserved
            self._arena = None

    def release(self) -> None:
        """Drop the shm arena reservation (engine shutdown)."""
        if self._arena is not None:
            self._arena.release()
            self._arena = None
            self.arena_bytes = 0

    # -- core alloc/free ------------------------------------------------
    def _evict_cached(self) -> Optional[int]:
        """Reclaim the least-recently-registered cached-free block."""
        if not self._cached:
            return None
        blk, _ = self._cached.popitem(last=False)
        key = self._key_of.pop(blk, None)
        if key is not None:
            self._by_key.pop(key, None)
            self._meta.pop(key, None)
            self._digest_of.pop(key, None)
        self.stats["evictions"] += 1
        return blk

    def can_alloc(self, n: int) -> bool:
        with self._lock:
            return len(self._free) + len(self._cached) >= n

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` exclusive blocks (refcount 1 each) or None if
        the pool can't cover it even after evicting cached prefixes —
        the engine queues the request instead of erroring."""
        with self._lock:
            if len(self._free) + len(self._cached) < n:
                self.stats["alloc_failures"] += 1
                return None
            out = []
            for _ in range(n):
                blk = self._free.popleft() if self._free \
                    else self._evict_cached()
                self._ref[blk] = 1
                out.append(blk)
            return out

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block.  A block reaching refcount 0
        returns to the free list unless it carries a prefix key — then
        it parks in the cached-free LRU with contents intact."""
        with self._lock:
            for blk in blocks:
                if blk <= 0:
                    continue
                self._ref[blk] -= 1
                if self._ref[blk] > 0:
                    continue
                self._ref[blk] = 0
                if self.prefix_sharing and blk in self._key_of:
                    self._cached[blk] = None
                    self._cached.move_to_end(blk)
                else:
                    self._free.append(blk)

    # -- prefix sharing -------------------------------------------------
    def lookup_prefix(self, tokens: List[int]
                      ) -> Tuple[List[int], int, Optional[Any]]:
        """Longest registered prefix of ``tokens``: returns (blocks,
        covered_tokens, meta) with every returned block increffed.
        Coverage is block-aligned except a whole-prompt hit, whose
        (possibly partial) tail block and stored last-token logits ride
        back too — the engine skips prefill entirely on that path."""
        if not self.prefix_sharing:
            return [], 0, None
        bs = self.block_size
        with self._lock:
            whole = tuple(tokens)
            if whole in self._by_key and len(tokens) % bs:
                # Whole-prompt key with a partial tail: grab the aligned
                # chain plus the tail.
                chain = self._chain_locked(tokens, len(tokens) // bs)
                if chain is not None:
                    tail = self._by_key[whole]
                    self._take_locked(tail)
                    blocks = chain + [tail]
                    self.stats["reuse_hits"] += len(blocks)
                    return blocks, len(tokens), self._meta.get(whole)
            # Longest aligned chain.
            n_full = len(tokens) // bs
            for k in range(n_full, 0, -1):
                chain = self._chain_locked(tokens, k)
                if chain is not None:
                    self.stats["reuse_hits"] += len(chain)
                    meta = (self._meta.get(whole)
                            if k * bs == len(tokens) else None)
                    return chain, k * bs, meta
            self.stats["reuse_misses"] += 1
            return [], 0, None

    def _chain_locked(self, tokens, k: int) -> Optional[List[int]]:
        """Incref + return the first k aligned blocks, or None if any
        link is missing (all-or-nothing so refcounts stay balanced)."""
        bs = self.block_size
        blocks = []
        for i in range(k):
            blk = self._by_key.get(tuple(tokens[:(i + 1) * bs]))
            if blk is None:
                for b in blocks:          # roll back increfs
                    self._drop_locked(b)
                return None
            blocks.append(blk)
        for b in blocks:
            self._take_locked(b)
        return blocks

    def _take_locked(self, blk: int) -> None:
        if self._ref[blk] == 0:
            self._cached.pop(blk, None)
        self._ref[blk] += 1

    def _drop_locked(self, blk: int) -> None:
        # Undo a _take_locked during chain rollback (no LRU re-park —
        # the block never left the caller's view).
        if self._ref[blk] > 0:
            self._ref[blk] -= 1
            if self._ref[blk] == 0 and blk in self._key_of:
                self._cached[blk] = None

    def register_prefix(self, tokens: List[int], blocks: List[int],
                        meta: Any = None) -> None:
        """Publish a prefilled prompt's blocks for reuse: aligned chunks
        keyed cumulatively, plus the whole-prompt key on the tail (which
        may be partial).  ``meta`` (last-token logits) is stored under
        the whole-prompt key.  Does NOT change refcounts — the caller
        still owns its references; blocks become cached-free when the
        last owner frees them."""
        if not self.prefix_sharing:
            return
        bs = self.block_size
        with self._lock:
            n_full = len(tokens) // bs
            for i in range(n_full):
                key = tuple(tokens[:(i + 1) * bs])
                self._register_locked(key, blocks[i])
            if len(tokens) % bs and len(blocks) > n_full:
                self._register_locked(tuple(tokens), blocks[n_full])
            if meta is not None:
                self._meta[tuple(tokens)] = meta

    def _register_locked(self, key: tuple, blk: int) -> None:
        old = self._by_key.get(key)
        if old == blk:
            return
        if old is not None:
            # Key collision with a different block: keep the existing
            # registration (its content already matches the key).
            return
        prev_key = self._key_of.get(blk)
        if prev_key is not None and prev_key != key:
            self._by_key.pop(prev_key, None)
            self._meta.pop(prev_key, None)
            self._digest_of.pop(prev_key, None)
        self._by_key[key] = blk
        self._key_of[blk] = key
        self._digest_of[key] = prefix_digest(key)

    def adopt(self, tokens: List[int], meta: Any = None
              ) -> Optional[List[int]]:
        """Adopt-path for KV frames received over the transfer plane
        (disaggregated prefill handoff / live migration): allocate
        blocks covering ``tokens``, register them as a reusable prefix,
        and return the block ids STILL REFERENCED — the engine scatters
        the received frame into them on-device, then calls ``free`` to
        park them cached-free (contents intact, LRU-evictable).  The
        next lookup of the prompt walks the normal prefix-hit path with
        zero recompute.  None when the pool can't cover the frame (the
        caller falls back to recompute)."""
        if not self.prefix_sharing or not tokens:
            return None
        bs = self.block_size
        need = -(-len(tokens) // bs)
        blocks = self.alloc(need)
        if blocks is None:
            return None
        self.register_prefix(tokens, blocks, meta=meta)
        return blocks

    def prefix_digests(self, limit: int = 0) -> List[str]:
        """Digests of the block-ALIGNED registered prefixes (the
        publishable half of the prefix map: whole-prompt partial-tail
        keys stay local — a remote replica can only splice aligned
        chains into a longer prompt).  Most-recently-registered last;
        ``limit`` > 0 keeps the newest that many (gauge-payload bound)."""
        with self._lock:
            out = [d for k, d in self._digest_of.items()
                   if len(k) % self.block_size == 0]
        if limit > 0 and len(out) > limit:
            out = out[-limit:]
        return out

    def unregister_block(self, blk: int) -> None:
        """Drop a block's prefix key (its content is about to diverge
        from the key — the sole-owner in-place-append path)."""
        with self._lock:
            key = self._key_of.pop(blk, None)
            if key is not None:
                self._by_key.pop(key, None)
                self._meta.pop(key, None)
                self._digest_of.pop(key, None)
            self._cached.pop(blk, None)

    def cow(self, blk: int) -> Tuple[int, bool]:
        """Prepare ``blk`` for in-place writes by its caller (who holds
        one reference).  Shared or registered blocks are duplicated:
        returns (new_block, True) and the caller must device-copy
        blk -> new_block and swap its table entry (its reference moves
        to the copy).  A sole-owner unregistered block is returned
        as-is: (blk, False)."""
        with self._lock:
            shared = self._ref[blk] > 1
            registered = blk in self._key_of
            if not shared and not registered:
                return blk, False
            if not shared and registered:
                # Sole owner of a registered block: cheaper to keep the
                # pristine copy for future hits only when a spare block
                # exists; otherwise just unregister and write in place.
                if not self._free and not self._cached:
                    key = self._key_of.pop(blk)
                    self._by_key.pop(key, None)
                    self._meta.pop(key, None)
                    self._digest_of.pop(key, None)
                    return blk, False
            new = self._free.popleft() if self._free \
                else self._evict_cached()
            if new is None:
                # Pool exhausted and the block is SHARED: the caller
                # must wait for capacity like any other allocation.
                raise MemoryError("KV pool exhausted during COW")
            self._ref[new] = 1
            # Caller's reference migrates to the copy.
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                if blk in self._key_of:
                    self._cached[blk] = None
                    self._cached.move_to_end(blk)
                else:
                    self._free.append(blk)
            self.stats["cow_copies"] += 1
            return new, True

    # -- introspection --------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            usable = self.num_blocks - 1
            free = len(self._free)
            cached = len(self._cached)
            active = usable - free - cached
            return {
                "blocks_total": usable,
                "blocks_free": free,
                "blocks_cached": cached,
                "blocks_active": active,
                "occupancy": round(active / usable, 4) if usable else 0.0,
                "prefixes_registered": len(self._by_key),
                "arena_bytes": self.arena_bytes,
                **self.stats,
            }
