"""Deployment definition + application graph node.

Reference surface: `@serve.deployment` (ref: python/ray/serve/api.py:244),
`Deployment.bind/options` and the app node passed to `serve.run`
(ref: serve/deployment.py, _private/deployment_graph_build.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0


class Application:
    """A bound deployment (callable + init args), ready for serve.run
    (ref: serve's Application from Deployment.bind)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(self, target: Callable, name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    @property
    def func_or_class(self):
        return self._target

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                ray_actor_options: Optional[dict] = None,
                autoscaling_config: Optional[AutoscalingConfig | dict] = None,
                **_ignored) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_target=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[AutoscalingConfig | dict] = None,
               **_ignored):
    """@serve.deployment decorator (ref: serve/api.py:244)."""
    def wrap(target):
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        nrep = num_replicas
        if nrep == "auto":
            nrep = (asc.min_replicas if asc else 1)
        cfg = DeploymentConfig(
            num_replicas=nrep,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {},
            autoscaling_config=asc)
        return Deployment(target, name or target.__name__, cfg)

    return wrap if _target is None else wrap(_target)
