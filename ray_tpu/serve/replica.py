"""Replica actor: hosts one copy of the user callable.

Reference: `ReplicaActor` + `UserCallableWrapper`
(ref: python/ray/serve/_private/replica.py:230, :716).  Tracks ongoing
request count (feeds the power-of-two router), exposes a health check,
serves STREAMING responses (generator results pulled in batches — the
analogue of the reference's streaming ObjectRefGenerator replies,
_raylet.pyx:272), and carries the multiplexed-model-id request context
(ref: serve/multiplex.py).
"""
from __future__ import annotations

import asyncio
import inspect
import pickle
import queue
import threading
import time
import uuid
from typing import Any, Dict, Optional

from ray_tpu.serve.multiplex import _model_id_ctx
from ray_tpu.util import tracing


class _Stream:
    """Background puller: drains the user generator into a queue so the
    actor thread never blocks inside user iteration code. The request's
    multiplexed-model-id context is re-established in the puller thread
    (generator bodies run HERE, not where the generator was created)."""

    def __init__(self, iterator, model_id: Optional[str] = None,
                 ctx: Optional[dict] = None, resumed: bool = False):
        self.ctx = ctx          # serve trace context (None = untraced)
        self.resumed = resumed
        self.q: "queue.Queue" = queue.Queue(maxsize=256)
        self.error: Optional[BaseException] = None
        self.finished = threading.Event()
        self.cancelled = threading.Event()
        self.last_touch = time.monotonic()

        def pull():
            if model_id:
                _model_id_ctx.set(model_id)
            try:
                for item in iterator:
                    while True:
                        if self.cancelled.is_set():
                            close = getattr(iterator, "close", None)
                            if callable(close):
                                close()
                            return
                        try:
                            self.q.put(item, timeout=0.5)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:  # noqa: BLE001
                self.error = e
            finally:
                self.finished.set()

        threading.Thread(target=pull, daemon=True).start()

    def next_batch(self, max_items: int, timeout_s: float) -> dict:
        self.last_touch = time.monotonic()
        items = []
        deadline = time.monotonic() + timeout_s
        while len(items) < max_items:
            try:
                remaining = max(0.0, deadline - time.monotonic())
                items.append(self.q.get(
                    timeout=remaining if not items else 0.0))
            except queue.Empty:
                if items or self.finished.is_set():
                    break
                if time.monotonic() >= deadline:
                    break
        done = (self.finished.is_set() and self.q.empty())
        if done and self.error is not None:
            raise self.error
        return {"items": items, "done": done}


class _RailsLane:
    """Pre-leased writer lane for rails streams: the serve analogue of a
    compiled-DAG stage host.  Replicas are actors, so — like the
    compiled DAG's ActorMethodNode stages, which run their loop inside
    the actor rather than on a separate leased worker — the decode tick
    loop is pinned HERE: a bounded set of dedicated pump threads, each
    dedicated to one stream for its life.  The width bound is the lane's
    lease: attach requests past it spill to the RPC pull path at
    admission (a mid-stream stage never loses its slot)."""

    def __init__(self, width: int):
        self.width = max(0, int(width))
        self._sem = threading.Semaphore(self.width)
        self._lock = threading.Lock()
        self.active = 0
        self.attached_total = 0
        self.spilled_total = 0

    def try_attach(self) -> bool:
        if self.width <= 0 or not self._sem.acquire(blocking=False):
            with self._lock:
                self.spilled_total += 1
            return False
        with self._lock:
            self.active += 1
            self.attached_total += 1
        return True

    def release(self) -> None:
        with self._lock:
            self.active -= 1
        self._sem.release()

    def stats(self) -> dict:
        with self._lock:
            return {"width": self.width, "active": self.active,
                    "attached_total": self.attached_total,
                    "spilled_total": self.spilled_total}


def _rails_writer(desc: dict):
    """Per-edge transport selection, mirroring the compiled DAG's
    `_writer_endpoint`: the ring always lives on the READER's (handle's)
    node, so a same-host replica mmaps it directly and a cross-host one
    pushes versioned raw frames through that node's daemon."""
    import os

    from ray_tpu.experimental.channel import Channel, RemoteChannelWriter

    if os.path.exists(desc["path"]):
        return Channel(desc["path"], desc["capacity"], desc["n_readers"],
                       desc["n_slots"])
    addr = desc.get("daemon_address")
    if not addr:
        return None
    return RemoteChannelWriter(addr, desc["path"], desc["capacity"],
                               desc["n_readers"], desc["n_slots"])


class Replica:
    def __init__(self, cls_or_fn, init_args, init_kwargs, replica_id: str):
        self.replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        self._start = time.time()
        self._streams: Dict[str, _Stream] = {}
        self._rails: Optional[_RailsLane] = None
        self._rails_lock = threading.Lock()
        self._draining = False
        # replica_id format: "serve:<app>#g<gen>#<idx>"
        self._app = replica_id.split(":", 1)[-1].split("#", 1)[0]
        # method name -> whether the resolved target accepts the
        # replica-injected `_serve_resume` / `_serve_trace` context.
        self._resume_aware: Dict[str, bool] = {}
        self._trace_aware: Dict[str, bool] = {}
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_func = False
        else:
            self._callable = cls_or_fn
            self._is_func = True
        ctx_hook = getattr(self._callable, "set_serve_context", None)
        if callable(ctx_hook):
            try:
                ctx_hook(self._app, replica_id)
            except Exception:  # noqa: BLE001 context is best-effort
                pass
        self._gauge_stop = threading.Event()
        threading.Thread(target=self._gauge_loop, daemon=True).start()

    # -- autoscaling gauges ---------------------------------------------
    def _gauge_loop(self, period_s: float = 1.0) -> None:
        """Push this replica's gauges (ongoing count + whatever the user
        callable's `engine_gauges()` reports, e.g. the paged engine's
        queue depth / KV occupancy) to the LOCAL node daemon; the
        daemon's syncer delta carries the aggregate to the GCS, where
        the controller reads one merged per-app view per autoscale tick
        instead of polling replicas."""
        app = self._app
        while not self._gauge_stop.wait(period_s):
            try:
                from ray_tpu.api import _global_worker, is_initialized

                if not is_initialized():
                    continue
                daemon = getattr(_global_worker(), "daemon", None)
                if daemon is None:  # local mode: no daemon, no syncer
                    return
                gauges = {"ongoing": float(self._ongoing),
                          "streams": float(len(self._streams))}
                hook = getattr(self._callable, "engine_gauges", None)
                if callable(hook):
                    for k, v in (hook() or {}).items():
                        gauges[k] = float(v)
                # Fold the hosted engine's cumulative stats into this
                # process's metric registry, then piggyback the whole
                # registry dump on the gauge push — the daemon merges
                # it into its federation payload so serve histograms /
                # KV counters reach `ray-tpu metrics --federated`
                # without a second RPC plane.
                from ray_tpu.serve import observability
                from ray_tpu.util.metrics import registry_dump

                eng = getattr(self._callable, "engine", None)
                if eng is not None and hasattr(eng, "engine_stats"):
                    observability.mirror_engine(eng, app)
                # Disagg role + published prefix digests ride the same
                # push (the cluster-wide prefix registry's write side).
                state = None
                sthook = getattr(self._callable, "serve_state", None)
                if callable(sthook):
                    try:
                        state = sthook() or None
                    except Exception:  # noqa: BLE001
                        state = None
                # Rails pull mode rides the same state payload so
                # `ray-tpu serve status` renders compiled/fallback per
                # replica next to the disagg role.
                if self._rails is not None:
                    from ray_tpu.core.config import get_config

                    rs = self._rails.stats()
                    rs["mode"] = ("compiled"
                                  if get_config().serve_rails_enabled
                                  else "fallback")
                    state = dict(state or {}, rails=rs)
                daemon.call("NodeDaemon", "report_serve_gauges",
                            app=app, replica=self.replica_id,
                            gauges=gauges, metrics=registry_dump(),
                            state=state, timeout=2)
            except Exception:  # noqa: BLE001 best-effort telemetry
                continue

    def _resolve(self, method: str):
        if self._is_func or method == "__call__":
            return self._callable
        return getattr(self._callable, method)

    def _invoke(self, method: str, args: tuple, kwargs: dict,
                model_id: Optional[str]) -> Any:
        token = _model_id_ctx.set(model_id) if model_id else None
        try:
            out = self._resolve(method)(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            return out
        finally:
            if token is not None:
                _model_id_ctx.reset(token)

    def _check_admission(self) -> None:
        if self._draining:
            from ray_tpu.exceptions import ReplicaDrainingError

            raise ReplicaDrainingError(self.replica_id)

    def handle_request(self, method: str, args: tuple, kwargs: dict,
                       model_id: Optional[str] = None,
                       trace: Optional[dict] = None) -> Any:
        self._check_admission()
        self._ongoing += 1
        self._total += 1
        try:
            with tracing.serve_span(trace, "serve.replica.request",
                                    replica=self.replica_id,
                                    method=method) as s:
                if trace and self._accepts_kw(method, "_serve_trace",
                                              self._trace_aware):
                    inj = tracing.child_ctx(trace, s)
                    kwargs = dict(kwargs, _serve_trace=(
                        dict(inj, app=self._app) if inj else None))
                return self._invoke(method, args, kwargs, model_id)
        finally:
            self._ongoing -= 1

    # -- streaming ------------------------------------------------------
    def _accepts_kw(self, method: str, kw: str,
                    cache: Dict[str, bool]) -> bool:
        """Whether the resolved target accepts the replica-injected
        keyword `kw` (explicitly or via **kwargs); cached per method."""
        cached = cache.get(method)
        if cached is not None:
            return cached
        try:
            params = inspect.signature(self._resolve(method)).parameters
            ok = (kw in params
                  or any(p.kind is inspect.Parameter.VAR_KEYWORD
                         for p in params.values()))
        except (TypeError, ValueError):
            ok = False
        cache[method] = ok
        return ok

    def _accepts_resume(self, method: str) -> bool:
        return self._accepts_kw(method, "_serve_resume",
                                self._resume_aware)

    def handle_request_streaming(self, method: str, args: tuple,
                                 kwargs: dict,
                                 model_id: Optional[str] = None,
                                 resume: Optional[dict] = None,
                                 trace: Optional[dict] = None,
                                 rails: Optional[dict] = None):
        """Start a streaming call; returns a stream id the caller pulls
        with stream_next().

        `resume` carries a failed-over stream's already-delivered prefix
        ({"offset": n, "items": [...]}).  Resume-aware callables (those
        accepting `_serve_resume`, e.g. LLMDeployment.stream) get it
        injected and recompute only the continuation; for everything
        else the generator is re-run and the first `offset` items are
        skipped server-side — either way the caller appends an
        exactly-once continuation.

        `rails` is a handle-created ring descriptor (decode on rails):
        when the lane attaches, frames push to the caller over the
        channel plane instead of stream_next pulls, and the reply is
        {"sid": ..., "rails": True/False} so the caller knows which pull
        mode this stream runs in.  A refused attach (kill switch off,
        lane at width, no route to the ring) is an admission-time spill:
        the stream serves normally over RPC."""
        self._check_admission()
        self._total += 1
        if resume and resume.get("request_id"):
            self._maybe_adopt_migration(resume)
        # Trace continuity across failover: a resumed stream keeps the
        # ORIGINAL request id as its trace id (the resume dict carries
        # it) so the whole request renders as one perfetto track; the
        # resumed=1 attribute marks post-failover spans.
        if resume and resume.get("request_id"):
            trace = tracing.serve_ctx(resume["request_id"],
                                      (trace or {}).get("span_id"),
                                      resumed=1) or trace
        attrs = {"replica": self.replica_id, "method": method}
        if resume:
            attrs["resumed"] = 1
            attrs["offset"] = int(resume.get("offset", 0))
        skip = 0
        if resume and self._accepts_resume(method):
            kwargs = dict(kwargs, _serve_resume=resume)
        elif resume:
            skip = int(resume.get("offset", 0))
        with tracing.serve_span(trace, "serve.replica.request",
                                **attrs) as s:
            if trace and self._accepts_kw(method, "_serve_trace",
                                          self._trace_aware):
                inj = tracing.child_ctx(trace, s)
                kwargs = dict(kwargs, _serve_trace=(
                    dict(inj, app=self._app) if inj else None))
            out = self._invoke(method, args, kwargs, model_id)
        if not hasattr(out, "__next__"):
            out = iter(out if hasattr(out, "__iter__") else [out])
        if skip > 0:
            import itertools

            out = itertools.islice(out, skip, None)
        sid = uuid.uuid4().hex
        self._gc_streams()
        st = _Stream(out, model_id=model_id,
                     ctx=trace, resumed=bool(resume))
        self._streams[sid] = st
        self._ongoing += 1
        if rails is not None:
            return {"sid": sid, "rails": self._rails_attach(sid, st, rails)}
        return sid

    # -- decode on rails ------------------------------------------------
    def _rails_lane(self) -> _RailsLane:
        with self._rails_lock:
            if self._rails is None:
                from ray_tpu.core.config import get_config

                self._rails = _RailsLane(
                    get_config().serve_rails_max_streams)
            return self._rails

    def _rails_attach(self, sid: str, st: _Stream, desc: dict) -> bool:
        """Pin this stream onto the rails lane: open the writer endpoint
        to the handle's ring and dedicate a pump thread.  Any failure is
        an admission-time spill (return False, stream stays on RPC)."""
        from ray_tpu.core.config import get_config

        if not get_config().serve_rails_enabled:
            return False
        lane = self._rails_lane()
        if not lane.try_attach():
            return False
        writer = None
        try:
            writer = _rails_writer(desc)
        except Exception:  # noqa: BLE001 bad descriptor / daemon gone
            writer = None
        if writer is None:
            lane.release()
            with lane._lock:
                lane.spilled_total += 1
            return False
        threading.Thread(target=self._rails_pump,
                         args=(sid, st, writer, lane), daemon=True).start()
        return True

    def _rails_pump(self, sid: str, st: _Stream, writer, lane: _RailsLane):
        """Pinned rails stage loop (the serve analogue of the compiled
        DAG's `_compiled_node_loop`): drain the stream's decode ticks
        into offset-tagged frames over versioned channel writes.  Errors
        ship in-band ({"err": e}); the reader decides whether they are
        retryable (drain/death -> resume over RPC) or terminal."""
        from ray_tpu.experimental.channel import (ChannelClosedError,
                                                  ChannelTimeoutError)

        def put(frame) -> bool:
            # The ring's slot window is the backpressure bound: a slow
            # consumer blocks the write, not the stream drop — retry
            # short slices until the stream itself is torn down.
            while True:
                try:
                    writer.write(frame, timeout=5.0)
                    return True
                except ChannelTimeoutError:
                    if st.cancelled.is_set():
                        return False
                except (ChannelClosedError, Exception):  # noqa: BLE001
                    return False

        offset = 0
        try:
            while True:
                try:
                    batch = st.next_batch(max_items=32, timeout_s=0.2)
                except BaseException as e:  # noqa: BLE001
                    try:
                        pickle.dumps(e)
                    except Exception:  # noqa: BLE001
                        e = RuntimeError(repr(e))
                    put({"err": e})
                    return
                n = len(batch["items"])
                if n or batch["done"]:
                    t0 = time.time()
                    if not put({"o": offset, "items": batch["items"],
                                "done": batch["done"]}):
                        return
                    offset += n
                    if n:
                        tracing.record_serve_span(
                            st.ctx, "serve.replica.rails_frame", t0,
                            items=n, done=batch["done"])
                if batch["done"]:
                    return
        finally:
            self._drop_stream(sid)
            lane.release()
            # The handle owns the ring's lifecycle; a cross-host writer
            # only needs its daemon RPC client released.
            client = getattr(writer, "_client", None)
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

    # -- live KV migration (serve/disagg.py) ----------------------------
    def _maybe_adopt_migration(self, resume: dict) -> None:
        """Warm-migration consume side: a draining replica published
        this stream's KV blocks as a ticket keyed by request id; adopt
        them into the local engine BEFORE the resumed context re-admits,
        so the engine's prefix hit covers the shipped blocks and
        recompute shrinks to the un-shipped tail.  Every failure path
        degrades to the ordinary recompute-as-extended-prompt resume."""
        from ray_tpu.core.config import get_config

        if not get_config().serve_kv_migrate_enabled:
            return
        adopt = getattr(self._callable, "adopt_kv", None)
        if not callable(adopt):
            return
        eng = getattr(self._callable, "engine", None)
        try:
            from ray_tpu.serve import observability
            from ray_tpu.serve.disagg import consume_migration_ticket

            t0 = time.time()
            ticket = consume_migration_ticket(resume["request_id"])
            if ticket is None:
                return
            adopt(ticket["tokens"], ticket["kv"], ticket["block_size"],
                  source="migrate")
            observability.observe_kv_migrate(
                self._app, max(0.0, time.time()
                               - float(ticket.get("ts") or time.time())))
            tracing.record_serve_span(
                tracing.serve_ctx(resume["request_id"]),
                "serve.kv.migrate", t0, time.time(), side="adopt",
                replica=self.replica_id,
                tokens=len(ticket["tokens"]))
        except Exception:  # noqa: BLE001 KVMigrationError / transport
            if eng is not None and hasattr(eng, "stats"):
                try:
                    eng.stats["migrate_fallbacks"] += 1
                except Exception:  # noqa: BLE001
                    pass

    def _export_migration_tickets(self) -> int:
        """Warm-migration publish side (drain path): snapshot every
        in-flight engine stream's KV blocks into GCS-KV tickets so the
        survivors can adopt instead of recompute."""
        from ray_tpu.core.config import get_config

        if not get_config().serve_kv_migrate_enabled:
            return 0
        eng = getattr(self._callable, "engine", None)
        exp = getattr(eng, "export_streams", None)
        if not callable(exp):
            return 0
        try:
            from ray_tpu.serve.disagg import publish_migration_tickets

            return publish_migration_tickets(self.replica_id, exp())
        except Exception:  # noqa: BLE001 degrade to recompute resume
            return 0

    def stream_next(self, stream_id: str, max_items: int = 32,
                    timeout_s: float = 1.0) -> dict:
        st = self._streams.get(stream_id)
        if st is None:
            return {"items": [], "done": True}
        t0 = time.time()
        try:
            batch = st.next_batch(max_items, timeout_s)
        except BaseException:
            self._drop_stream(stream_id)
            raise
        if batch["items"] or batch["done"]:
            # One span per DELIVERED batch (empty polls are elided so a
            # slow generator doesn't flood the trace with idle waits).
            attrs = {"items": len(batch["items"]), "done": batch["done"]}
            if st.resumed:
                attrs["resumed"] = 1
            tracing.record_serve_span(st.ctx, "serve.replica.stream_next",
                                      t0, **attrs)
            if batch["items"]:
                from ray_tpu.serve import observability

                observability.observe_phase(self._app, "stream_transport",
                                            time.time() - t0)
        if batch["done"]:
            self._drop_stream(stream_id)
        return batch

    def cancel_stream(self, stream_id: str) -> bool:
        self._drop_stream(stream_id)
        return True

    def _drop_stream(self, stream_id: str) -> None:
        st = self._streams.pop(stream_id, None)
        if st is not None:
            st.cancelled.set()  # unblocks + closes the puller's generator
            self._ongoing = max(0, self._ongoing - 1)

    def _gc_streams(self, idle_s: float = 300.0) -> None:
        now = time.monotonic()
        for sid, st in list(self._streams.items()):
            if now - st.last_touch > idle_s:
                self._drop_stream(sid)

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Stop admission and retire this replica gracefully: in-flight
        requests/streams keep running up to `timeout_s` (default
        RAY_TPU_SERVE_DRAIN_TIMEOUT_S), then the process exits.  Clients
        still attached past the deadline observe ActorDiedError and
        migrate-by-recompute through the handle's stream-resume path.
        Self-terminating: a controller that dies right after sending the
        drain RPC leaks no orphan replica."""
        from ray_tpu.core.config import get_config

        knobs = get_config()
        if timeout_s is None:
            timeout_s = knobs.serve_drain_timeout_s
        first = not self._draining
        self._draining = True
        migrated = 0
        if first and self._streams and knobs.serve_kv_migrate_enabled:
            # Live migration: publish every in-flight stream's KV blocks
            # as tickets, then fail the streams with the typed draining
            # error — attached clients drain what's already queued, hit
            # the error, and the handle's resume path re-admits them on
            # a survivor that adopts the shipped blocks (recompute stays
            # the fallback for anything without a ticket).
            migrated = self._export_migration_tickets()
            from ray_tpu.exceptions import ReplicaDrainingError

            for st in list(self._streams.values()):
                st.error = ReplicaDrainingError(self.replica_id)
                st.cancelled.set()
                st.finished.set()

        def reaper():
            import os

            deadline = time.monotonic() + max(0.0, float(timeout_s))
            while time.monotonic() < deadline:
                if self._ongoing <= 0 and not self._streams:
                    break
                time.sleep(0.1)
            # Linger so in-flight stream_next RPCs observe the typed
            # draining error (and fresh tickets get consumed) before
            # the process exits out from under them.
            if migrated:
                time.sleep(max(0.0, knobs.serve_kv_migrate_linger_s))
            self._gauge_stop.set()
            os._exit(0)

        if first:
            threading.Thread(target=reaper, daemon=True).start()
        return dict(self.stats(), migrated_tickets=migrated)

    def stats(self) -> dict:
        out = {"replica_id": self.replica_id, "ongoing": self._ongoing,
               "total": self._total, "streams": len(self._streams),
               "draining": self._draining,
               "uptime": time.time() - self._start}
        if self._rails is not None:
            out["rails"] = self._rails.stats()
        return out

    def getpid(self) -> int:
        """Worker-process pid — lets chaos tooling SIGKILL the actual
        process (crash semantics) rather than an actor-level kill."""
        import os

        return os.getpid()

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if callable(user_check):
            user_check()
        return True

    def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self._callable, "reconfigure", None)
        if callable(hook):
            hook(user_config)
