"""Replica actor: hosts one copy of the user callable.

Reference: `ReplicaActor` + `UserCallableWrapper`
(ref: python/ray/serve/_private/replica.py:230, :716).  Tracks ongoing
request count (feeds the power-of-two router) and exposes a health check.
"""
from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any


class Replica:
    def __init__(self, cls_or_fn, init_args, init_kwargs, replica_id: str):
        self.replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        self._start = time.time()
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_func = False
        else:
            self._callable = cls_or_fn
            self._is_func = True

    def handle_request(self, method: str, args: tuple, kwargs: dict) -> Any:
        self._ongoing += 1
        self._total += 1
        try:
            if self._is_func or method == "__call__":
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            return out
        finally:
            self._ongoing -= 1

    def stats(self) -> dict:
        return {"replica_id": self.replica_id, "ongoing": self._ongoing,
                "total": self._total, "uptime": time.time() - self._start}

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if callable(user_check):
            user_check()
        return True

    def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self._callable, "reconfigure", None)
        if callable(hook):
            hook(user_config)
