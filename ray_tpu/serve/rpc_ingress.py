"""Native RPC ingress for Serve: the gRPC-ingress analogue.

Analogue of the reference gRPC proxy (ref: serve/_private/proxy.py:533
gRPCProxy — a second, binary ingress next to HTTP for low-overhead
service-to-service calls). The TPU-native equivalent speaks the
framework's own length-prefixed frame protocol, so any client that
already talks to the cluster (Python drivers, the C++ client, other
services) can invoke deployments without HTTP overhead:

    service "ServeIngress":
      invoke(app, method, args, kwargs) -> deployment result
      stream_invoke(app, method, args, kwargs) -> streamed items

Runs inside an actor like the HTTP proxy, with its own RpcServer.
"""
from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional


class RpcIngress:
    """Actor: native-protocol ingress routing to deployment handles."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 executor_threads: int = 32):
        self._handles: Dict[str, object] = {}
        self._executor = ThreadPoolExecutor(max_workers=executor_threads,
                                            thread_name_prefix="ingress")
        # Streams park a thread in next() for their whole lifetime: a
        # separate pool keeps slow streams from starving unary invokes
        # (same split as http_proxy's _stream_executor).
        self._stream_executor = ThreadPoolExecutor(
            max_workers=executor_threads,
            thread_name_prefix="ingress-stream")
        self._host = host
        self._want_port = port
        self._port: Optional[int] = None
        self._started = threading.Event()
        threading.Thread(target=self._serve_thread, daemon=True).start()
        if not self._started.wait(30):
            raise RuntimeError("RPC ingress failed to start")

    def _serve_thread(self) -> None:
        from ray_tpu.core.distributed.rpc import RpcServer

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = RpcServer(self._host, self._want_port)
        server.add_service("ServeIngress", _IngressService(self))

        async def start():
            self._port = await server.start()
            self._started.set()

        loop.run_until_complete(start())
        loop.run_forever()

    def _handle_for(self, app: str):
        handle = self._handles.get(app)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(app)
            self._handles[app] = handle
        return handle

    def port(self) -> int:
        return self._port

    def stop(self) -> bool:
        return True


class _IngressService:
    def __init__(self, ingress: RpcIngress):
        self._ingress = ingress

    async def invoke(self, app: str, target_method: str = "__call__",
                     args: tuple = (), kwargs: Optional[dict] = None):
        """Unary deployment call; blocks on the handle in the executor
        pool (handle calls ride the runtime and may wait on replicas)."""
        handle = self._ingress._handle_for(app)
        if target_method != "__call__":
            handle = handle.options(method_name=target_method)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._ingress._executor,
            lambda: handle.remote(*args, **(kwargs or {})).result())

    async def stream_invoke(self, app: str,
                            target_method: str = "__call__",
                            args: tuple = (),
                            kwargs: Optional[dict] = None):
        """Server-streaming deployment call (generator methods)."""
        handle = self._ingress._handle_for(app)
        if target_method != "__call__":
            handle = handle.options(method_name=target_method)
        loop = asyncio.get_running_loop()
        stream = await loop.run_in_executor(
            self._ingress._stream_executor,
            lambda: handle.remote_streaming(*args, **(kwargs or {})))
        it = iter(stream)
        try:
            while True:
                item = await loop.run_in_executor(
                    self._ingress._stream_executor,
                    lambda: next(it, _SENTINEL))
                if item is _SENTINEL:
                    return
                yield item
        finally:
            # Client disconnect/CANCEL closes this generator: free the
            # replica-side stream + the handle's outstanding counter
            # (same discipline as http_proxy.py).
            stream.cancel()


_SENTINEL = object()
