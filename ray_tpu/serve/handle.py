"""DeploymentHandle + power-of-two-choices routing.

Reference: `DeploymentHandle`/`DeploymentResponse` (ref:
python/ray/serve/handle.py:694,436) and
`PowerOfTwoChoicesReplicaScheduler` (ref: _private/replica_scheduler/
pow_2_scheduler.py:49): sample two replicas, pick the lower queue.  Queue
depth here is the handle's own outstanding-count per replica (cheap local
signal), refreshed against controller routing state on version change.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import get_or_create_controller


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef."""

    def __init__(self, ref, on_done=None):
        self._ref = ref
        self._on_done = on_done
        self._done = False

    def result(self, timeout: Optional[float] = None) -> Any:
        try:
            out = ray_tpu.get(self._ref, timeout=timeout)
        finally:
            self._settle()
        return out

    def _settle(self):
        if not self._done and self._on_done:
            self._done = True
            self._on_done()

    def _to_object_ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, app_name: str, method_name: str = "__call__"):
        self._app = app_name
        self._method = method_name
        self._controller = get_or_create_controller()
        self._version = -2
        self._replicas: Dict[str, Any] = {}
        self._outstanding: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._last_stats_push = 0.0
        self._last_refresh = 0.0
        self._refresh_ttl = 0.5

    # handle.method_name.remote(...) sugar
    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentHandle.__new_method(self, item)

    @staticmethod
    def __new_method(parent: "DeploymentHandle", method: str):
        h = DeploymentHandle.__new__(DeploymentHandle)
        h.__dict__.update(parent.__dict__)
        h._method = method
        return h

    def options(self, *, method_name: Optional[str] = None, **_ignored):
        if method_name:
            return DeploymentHandle.__new_method(self, method_name)
        return self

    def _refresh(self, force: bool = False):
        # TTL throttle: the controller round-trip must not be on every
        # request's critical path (the long-poll analogue).
        now = time.monotonic()
        if not force and now - self._last_refresh < self._refresh_ttl:
            return
        self._last_refresh = now
        routing = ray_tpu.get(
            self._controller.get_routing.remote(self._app), timeout=30)
        with self._lock:
            if routing["version"] != self._version or force:
                names = routing["replicas"]
                self._replicas = {}
                for n in names:
                    try:
                        self._replicas[n] = ray_tpu.get_actor(n)
                    except Exception:  # noqa: BLE001
                        pass
                self._outstanding = {n: self._outstanding.get(n, 0)
                                     for n in self._replicas}
                self._version = routing["version"]

    def _pick_replica(self):
        deadline = time.monotonic() + 30
        while True:
            # Sample and index under one lock hold — a concurrent _refresh
            # may rebuild self._replicas between reads otherwise.
            with self._lock:
                names = list(self._replicas)
                if names:
                    if len(names) == 1:
                        pick = names[0]
                    else:
                        a, b = random.sample(names, 2)
                        pick = (a if self._outstanding.get(a, 0)
                                <= self._outstanding.get(b, 0) else b)
                    self._outstanding[pick] = \
                        self._outstanding.get(pick, 0) + 1
                    return pick, self._replicas[pick]
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for app {self._app!r} after 30s")
            self._refresh(force=True)
            time.sleep(0.1)

    def _push_stats(self):
        now = time.time()
        if now - self._last_stats_push < 1.0:
            return
        self._last_stats_push = now
        total = sum(self._outstanding.values())
        try:
            self._controller.record_autoscale_stats.remote(self._app, total)
        except Exception:  # noqa: BLE001
            pass

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._refresh()
        name, replica = self._pick_replica()
        self._push_stats()

        def on_done(n=name):
            with self._lock:
                self._outstanding[n] = max(0, self._outstanding.get(n, 1) - 1)

        try:
            ref = replica.handle_request.remote(self._method, args, kwargs)
        except Exception:
            on_done()
            # replica may have just died; refresh and retry once
            self._refresh(force=True)
            name, replica = self._pick_replica()
            ref = replica.handle_request.remote(self._method, args, kwargs)
        return DeploymentResponse(ref, on_done)
