"""DeploymentHandle + power-of-two-choices routing.

Reference: `DeploymentHandle`/`DeploymentResponse` (ref:
python/ray/serve/handle.py:694,436) and
`PowerOfTwoChoicesReplicaScheduler` (ref: _private/replica_scheduler/
pow_2_scheduler.py:49): sample two replicas, pick the lower queue.  Queue
depth here is the handle's own outstanding-count per replica (cheap local
signal), refreshed against controller routing state on version change.
"""
from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.util import tracing


def _retry_backoff(attempt: int) -> float:
    """Capped exponential + jitter between replica-failure retries
    (RAY_TPU_SERVE_RETRY_BACKOFF_*, same shape as the elastic-train
    gang-restart backoff)."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    delay = min(cfg.serve_retry_backoff_max_s,
                cfg.serve_retry_backoff_initial_s
                * cfg.serve_retry_backoff_multiplier ** attempt)
    jitter = cfg.serve_retry_backoff_jitter
    return max(0.0, delay * (1 + random.uniform(-jitter, jitter)))


def _retryable_errors():
    import ray_tpu.exceptions as rexc

    return (rexc.ActorDiedError, rexc.ActorUnavailableError,
            rexc.ReplicaDrainingError)


def _rails_ring():
    """Decode on rails, reader side: pre-create a shm ring on THIS node
    (reads are a local mmap poll, exactly the compiled DAG's placement
    rule) and describe it for the replica's writer endpoint — a
    same-host replica mmaps the path, a cross-host one pushes versioned
    frames through this node's daemon.  Returns None when rails are off
    or the ring can't be built (the stream then admits on RPC pulls)."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    if not cfg.serve_rails_enabled:
        return None
    try:
        from ray_tpu.experimental.channel import Channel

        ch = Channel.create(1, capacity=cfg.serve_rails_capacity_bytes)
    except Exception:  # noqa: BLE001 — no /dev/shm etc.
        return None
    addr = None
    try:
        from ray_tpu.api import _global_worker

        addr = getattr(_global_worker(), "daemon_address", None)
    except Exception:  # noqa: BLE001 local mode
        addr = None
    return {"ch": ch,
            "desc": {"path": ch.path, "capacity": ch.capacity,
                     "n_readers": ch.n_readers, "n_slots": ch.n_slots,
                     "daemon_address": addr}}


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef.

    Replica death between routing and completion is retried through the
    handle (refresh + re-pick), like the reference router's transparent
    replica-failure retries (ref: _private/router.py).  Attempts and
    backoff come from RAY_TPU_SERVE_RETRY_MAX /
    RAY_TPU_SERVE_RETRY_BACKOFF_*; a draining replica (graceful
    downscale) is retried the same way as a dead one."""

    def __init__(self, ref, on_done=None, retry_fn=None):
        self._ref = ref
        self._on_done = on_done
        self._retry_fn = retry_fn
        self._done = False

    def result(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu.core.config import get_config

        attempts = max(1, get_config().serve_retry_max)
        try:
            for attempt in range(attempts):
                try:
                    out = ray_tpu.get(self._ref, timeout=timeout)
                    break
                except _retryable_errors():
                    if self._retry_fn is None or attempt == attempts - 1:
                        raise
                    time.sleep(_retry_backoff(attempt))
                    self._ref = self._retry_fn()
        finally:
            self._settle()
        return out

    def _settle(self):
        if not self._done and self._on_done:
            self._done = True
            self._on_done()

    def _to_object_ref(self):
        return self._ref


def _rebuild_handle(app: str, method: str, model_id, stream
                    ) -> "DeploymentHandle":
    h = DeploymentHandle(app, method)
    h._model_id = model_id
    h._stream = bool(stream)
    return h


class DeploymentHandle:
    def __init__(self, app_name: str, method_name: str = "__call__"):
        self._app = app_name
        self._method = method_name
        self._controller = get_or_create_controller()
        self._version = -2
        self._replicas: Dict[str, Any] = {}
        self._outstanding: Dict[str, int] = {}
        self._lock = threading.Lock()
        # Stable id for controller-side per-handle stats (TTL'd there:
        # when this handle goes away its count ages out).
        self._handle_id = uuid.uuid4().hex
        self._last_stats_push = 0.0
        self._last_refresh = 0.0
        self._refresh_ttl = 0.5
        self._model_id: Optional[str] = None
        self._stream = False
        # model_id -> replica name that recently served it (multiplexed
        # locality, ref: pow_2_scheduler.py multiplex-aware candidates).
        self._model_affinity: Dict[str, str] = {}
        # Cluster-wide prefix registry read side (serve/disagg.py):
        # aligned-prefix digest -> owning replica, refreshed with the
        # routing table; prefix-warm requests prefer the owner.
        self._prefix_owners: Dict[str, str] = {}
        self._kv_block_size = 0

    def __reduce__(self):
        # Handles cross process boundaries by RECONSTRUCTION, not state
        # copy: a replica receiving a handle as an init arg (deployment
        # graph composition) resolves the controller and routing table
        # in its own process (ref: serve handles pickle the same way).
        # Options set via .options() (model affinity, streaming) are
        # part of the handle's contract and must survive the trip.
        return (_rebuild_handle, (self._app, self._method,
                                  self._model_id, self._stream))

    # handle.method_name.remote(...) sugar
    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentHandle.__new_method(self, item)

    @staticmethod
    def __new_method(parent: "DeploymentHandle", method: str):
        h = DeploymentHandle.__new__(DeploymentHandle)
        h.__dict__.update(parent.__dict__)
        h._method = method
        return h

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None, **_ignored):
        h = self
        if method_name:
            h = DeploymentHandle.__new_method(h, method_name)
        if multiplexed_model_id is not None or stream is not None:
            if h is self:
                h = DeploymentHandle.__new_method(self, self._method)
            if multiplexed_model_id is not None:
                h._model_id = multiplexed_model_id
            if stream is not None:
                h._stream = stream
        return h

    def _refresh(self, force: bool = False):
        # TTL throttle: the controller round-trip must not be on every
        # request's critical path (the long-poll analogue).
        now = time.monotonic()
        if not force and now - self._last_refresh < self._refresh_ttl:
            return
        self._last_refresh = now
        try:
            routing = ray_tpu.get(
                self._controller.get_routing.remote(self._app), timeout=30)
        except Exception:  # noqa: BLE001
            # Controller died: re-resolve (get_or_create restarts it; the
            # new one recovers targets from the GCS KV and re-adopts live
            # replicas).  Version counters reset across controller
            # incarnations, so force a routing rebuild.  If the
            # controller plane is entirely down, keep serving from the
            # cached replica set rather than failing the request path.
            try:
                self._controller = get_or_create_controller()
                routing = ray_tpu.get(
                    self._controller.get_routing.remote(self._app),
                    timeout=30)
                force = True
                self._version = -2
            except Exception:  # noqa: BLE001
                if self._replicas:
                    return
                raise
        with self._lock:
            # Owner map updates on EVERY refresh (replicas publish new
            # prefixes without a routing-version bump).
            self._prefix_owners = routing.get("prefix_owners") or {}
            self._kv_block_size = int(routing.get("kv_block_size") or 0)
            if routing["version"] != self._version or force:
                names = routing["replicas"]
                self._replicas = {}
                for n in names:
                    try:
                        self._replicas[n] = ray_tpu.get_actor(n)
                    except Exception:  # noqa: BLE001
                        pass
                self._outstanding = {n: self._outstanding.get(n, 0)
                                     for n in self._replicas}
                self._version = routing["version"]

    def _prefix_hint(self, args, kwargs):
        """Prefix-affinity routing input: the replica (if any) that owns
        registered KV blocks for this request's longest aligned token
        prefix.  Returns (owner_or_None, applicable) — `applicable` is
        True when the request was token-shaped and the registry had a
        block size to align against (so the caller can count
        remote_prefix_hit/miss only for requests that could match)."""
        from ray_tpu.core.config import get_config

        if not get_config().serve_prefix_registry_enabled:
            return None, False
        with self._lock:
            owners = dict(self._prefix_owners)
            bs = self._kv_block_size
        req = (args[0] if args and isinstance(args[0], dict)
               else kwargs.get("request"))
        tokens = (req or {}).get("tokens") if isinstance(req, dict) else None
        if not tokens or not isinstance(tokens, (list, tuple)) or not bs:
            return None, False
        if not owners:
            return None, True
        from ray_tpu.serve.disagg import request_digests

        # Longest covered prefix first: route to the replica holding
        # the deepest warm chain.
        for _, digest in request_digests(list(tokens), bs):
            rid = owners.get(digest)
            if rid:
                return rid, True
        return None, True

    def _count_prefix_route(self, prefer, applicable, pick) -> None:
        if not applicable:
            return
        try:
            from ray_tpu.serve import observability

            observability.count_kv_event(
                self._app, "remote_prefix_hit"
                if prefer is not None and pick == prefer
                else "remote_prefix_miss")
        except Exception:  # noqa: BLE001 best-effort telemetry
            pass

    def _pick_replica(self, exclude: Optional[str] = None,
                      prefer: Optional[str] = None):
        deadline = time.monotonic() + 30
        while True:
            # Sample and index under one lock hold — a concurrent _refresh
            # may rebuild self._replicas between reads otherwise.
            with self._lock:
                names = list(self._replicas)
                # Failover re-picks avoid the replica that just failed —
                # unless it is the only one left (it may have restarted).
                if exclude in names and len(names) > 1:
                    names.remove(exclude)
                if names:
                    pick = None
                    # Multiplexed locality: prefer the replica that already
                    # holds this model (avoids a reload), unless it is
                    # clearly the most loaded one.
                    if self._model_id:
                        cand = self._model_affinity.get(self._model_id)
                        if cand in names:
                            load = self._outstanding.get(cand, 0)
                            if load <= 2 + min(
                                    (self._outstanding.get(n, 0)
                                     for n in names), default=0):
                                pick = cand
                    # Prefix affinity: the replica already holding this
                    # request's KV blocks skips the prefill entirely —
                    # worth following unless it is clearly overloaded
                    # (same guard as model affinity).
                    if pick is None and prefer in names:
                        load = self._outstanding.get(prefer, 0)
                        if load <= 2 + min(
                                (self._outstanding.get(n, 0)
                                 for n in names), default=0):
                            pick = prefer
                    if pick is None:
                        if len(names) == 1:
                            pick = names[0]
                        else:
                            a, b = random.sample(names, 2)
                            pick = (a if self._outstanding.get(a, 0)
                                    <= self._outstanding.get(b, 0) else b)
                        if self._model_id:
                            self._model_affinity[self._model_id] = pick
                            while len(self._model_affinity) > 1024:
                                self._model_affinity.pop(
                                    next(iter(self._model_affinity)))
                    self._outstanding[pick] = \
                        self._outstanding.get(pick, 0) + 1
                    return pick, self._replicas[pick]
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for app {self._app!r} after 30s")
            self._refresh(force=True)
            time.sleep(0.1)

    def _push_stats(self):
        now = time.time()
        if now - self._last_stats_push < 1.0:
            return
        self._last_stats_push = now
        total = sum(self._outstanding.values())
        try:
            self._controller.record_autoscale_stats.remote(
                self._app, total, handle_id=self._handle_id)
        except Exception:  # noqa: BLE001
            pass

    def remote(self, *args, **kwargs):
        if self._stream:
            return self.remote_streaming(*args, **kwargs)
        # Reserved keys ride the kwargs channel from the proxy (popped
        # here so user callables never see them); direct handle calls
        # mint their own request id so `ray-tpu serve trace` works
        # without the HTTP front.
        request_id = kwargs.pop("_request_id", None) or uuid.uuid4().hex
        ctx = kwargs.pop("_trace", None) or tracing.serve_ctx(request_id)
        self._refresh()
        prefer, applicable = self._prefix_hint(args, kwargs)
        with tracing.serve_span(ctx, "serve.handle.route",
                                app=self._app, method=self._method) as s:
            name, replica = self._pick_replica(prefer=prefer)
            trace = tracing.child_ctx(ctx, s)
        self._count_prefix_route(prefer, applicable, name)
        self._push_stats()
        # Mutable cell: retries re-route to a new replica; on_done must
        # decrement whichever replica CURRENTLY carries the request.
        holder = {"name": name}

        def on_done():
            with self._lock:
                n = holder["name"]
                self._outstanding[n] = max(0, self._outstanding.get(n, 1) - 1)

        def retry():
            on_done()  # release the failed pick before re-picking
            self._refresh(force=True)
            with tracing.serve_span(ctx, "serve.handle.resume",
                                    app=self._app, resumed=1):
                name2, replica2 = self._pick_replica()
            holder["name"] = name2
            return replica2.handle_request.remote(
                self._method, args, kwargs, model_id=self._model_id,
                trace=trace)

        try:
            ref = replica.handle_request.remote(
                self._method, args, kwargs, model_id=self._model_id,
                trace=trace)
        except Exception:
            # replica may have just died; refresh and retry once
            ref = retry()
        return DeploymentResponse(ref, on_done, retry_fn=retry)

    def remote_streaming(self, *args, **kwargs) -> "StreamingResponse":
        """Streaming call: the replica runs a generator; items arrive in
        pulled batches (ref: streaming ObjectRefGenerator replies,
        proxy.py:747 streaming responses).  The response carries a
        request id and its emitted-item offset; replica death mid-stream
        fails over to a surviving replica via the resume protocol
        (re-admit args + emitted prefix, dedupe the overlap)."""
        # The request id doubles as the trace id; a proxy-minted one
        # arrives via the reserved `_request_id`/`_trace` kwargs, direct
        # handle users get a fresh one (same id the resume protocol and
        # `ray-tpu serve trace` key on).
        request_id = kwargs.pop("_request_id", None) or uuid.uuid4().hex
        ctx = kwargs.pop("_trace", None) or tracing.serve_ctx(request_id)
        self._refresh()
        prefer, applicable = self._prefix_hint(args, kwargs)
        with tracing.serve_span(ctx, "serve.handle.route",
                                app=self._app, method=self._method) as s:
            name, replica = self._pick_replica(prefer=prefer)
            trace = tracing.child_ctx(ctx, s)
        self._count_prefix_route(prefer, applicable, name)
        self._push_stats()
        # Mutable cell: failovers re-route to a new replica; on_done must
        # decrement whichever replica CURRENTLY carries the stream.
        holder = {"name": name}

        def on_done():
            with self._lock:
                n = holder["name"]
                self._outstanding[n] = max(0, self._outstanding.get(n, 1) - 1)

        def resume_fn(emitted):
            failed = holder["name"]
            on_done()  # release the failed pick before re-picking
            self._refresh(force=True)
            with tracing.serve_span(ctx, "serve.handle.resume",
                                    app=self._app, resumed=1,
                                    offset=len(emitted)) as rs:
                name2, replica2 = self._pick_replica(exclude=failed)
                trace2 = tracing.child_ctx(ctx, rs)
            holder["name"] = name2
            self._push_stats()
            try:
                from ray_tpu.serve import observability

                observability.metrics()["resumes"].inc(
                    1, {"app": self._app})
            except Exception:  # noqa: BLE001 best-effort telemetry
                pass
            sid_ref2 = replica2.handle_request_streaming.remote(
                self._method, args, kwargs, model_id=self._model_id,
                resume={"request_id": request_id,
                        "offset": len(emitted), "items": list(emitted)},
                trace=trace2)
            return replica2, sid_ref2

        rails = _rails_ring()
        sid_ref = replica.handle_request_streaming.remote(
            self._method, args, kwargs, model_id=self._model_id,
            trace=trace,
            **({"rails": rails["desc"]} if rails else {}))
        return StreamingResponse(replica, sid_ref, on_done,
                                 resume_fn=resume_fn,
                                 request_id=request_id, rails=rails)


class StreamingResponse:
    """Iterator over a replica-side stream; batches pulls to amortize the
    per-call RPC cost.

    Fault tolerance: the response keeps the items it has already yielded
    (the resume prefix).  When the serving replica dies, becomes
    unreachable, or refuses admission because it is draining, the
    iterator re-admits the request on another replica with
    `resume={"offset": N, "items": [...]}` — the engine recomputes KV
    for prompt + emitted tokens and continues from there — so consumers
    (including the HTTP proxy mid-stream) observe one exactly-once item
    sequence across the failover."""

    def __init__(self, replica, sid_ref, on_done, max_items: int = 32,
                 resume_fn=None, request_id: Optional[str] = None,
                 rails: Optional[dict] = None):
        self._replica = replica
        self._sid_ref = sid_ref
        self._sid = None
        self._on_done = on_done
        self._max_items = max_items
        self._settled = False
        self._resume_fn = resume_fn
        self._emitted: list = []
        self._rails = rails        # {"ch": Channel, "desc": {...}} | None
        self._rails_offset = 0     # items landed over the ring so far
        self.rails = False         # pull mode currently in effect
        self.rails_used = False    # ever attached (survives the spill)
        self.request_id = request_id or uuid.uuid4().hex
        self.resumes = 0  # failovers survived (observability/tests)

    def _settle(self):
        if not self._settled:
            self._settled = True
            if self._on_done:
                self._on_done()

    def _drop_rails(self):
        """Release the ring (normal end, cancel, or spill to RPC).  The
        replica-side pump observes the close as ChannelClosedError on
        its next write and retires its lane slot."""
        r, self._rails = self._rails, None
        self.rails = False
        if r is not None:
            try:
                r["ch"].close()
                r["ch"].unlink()
            except Exception:  # noqa: BLE001
                pass

    def __del__(self):
        self._drop_rails()

    def cancel(self):
        if self._settled:
            return  # already finished or cancelled
        if self._sid is not None:
            try:
                self._replica.cancel_stream.remote(self._sid)
            except Exception:  # noqa: BLE001
                pass
        self._drop_rails()
        self._settle()

    def _rails_next(self, pull_timeout: float) -> dict:
        """One pull over the ring: poll in short slices, probing replica
        liveness on idle slices so a SIGKILLed replica surfaces as the
        same typed error the RPC path would raise (-> resume ladder).
        Error frames re-raise in-band: retryable ones (draining, died)
        resume, user exceptions propagate to the consumer."""
        from ray_tpu.core.config import get_config
        from ray_tpu.experimental.channel import (ChannelClosedError,
                                                  ChannelTimeoutError)
        import ray_tpu.exceptions as rexc

        cfg = get_config()
        deadline = time.monotonic() + pull_timeout
        next_probe = time.monotonic() + cfg.serve_rails_probe_s
        while True:
            try:
                frame = self._rails["ch"].read(
                    timeout=cfg.serve_rails_tick_s, reader_idx=0)
            except ChannelTimeoutError:
                now = time.monotonic()
                if now >= deadline:
                    raise TimeoutError(
                        f"rails stream idle for {pull_timeout}s")
                if now >= next_probe:
                    ray_tpu.get(self._replica.check_health.remote(),
                                timeout=cfg.serve_rails_probe_s + 5.0)
                    next_probe = time.monotonic() + cfg.serve_rails_probe_s
                continue
            except ChannelClosedError:
                raise rexc.ActorUnavailableError(
                    "rails ring closed under a live stream")
            err = frame.get("err") if isinstance(frame, dict) else None
            if err is not None:
                raise err
            if int(frame.get("o", -1)) != self._rails_offset:
                # Out-of-order frame: never expected from the versioned
                # ring — treat as lane loss, not silent corruption.
                raise rexc.ActorUnavailableError(
                    f"rails frame offset {frame.get('o')} != "
                    f"{self._rails_offset}")
            self._rails_offset += len(frame["items"])
            return frame

    def __iter__(self):
        from ray_tpu.core.config import get_config

        cfg = get_config()
        pull_timeout = cfg.serve_request_deadline_s
        max_resumes = max(1, cfg.serve_retry_max)
        try:
            while True:
                try:
                    if self._sid is None:
                        sid = ray_tpu.get(self._sid_ref,
                                          timeout=pull_timeout)
                        if isinstance(sid, dict):
                            self.rails = (bool(sid.get("rails"))
                                          and self._rails is not None)
                            self.rails_used |= self.rails
                            sid = sid["sid"]
                        self._sid = sid
                        if not self.rails:
                            self._drop_rails()  # admission-time spill
                    if self.rails:
                        batch = self._rails_next(pull_timeout)
                    else:
                        batch = ray_tpu.get(
                            self._replica.stream_next.remote(
                                self._sid, max_items=self._max_items),
                            timeout=pull_timeout)
                except _retryable_errors():
                    # Lane loss / drain / replica death: spill to the
                    # ordinary RPC path and re-admit through the resume
                    # protocol (PR 9 machinery, unchanged) — the emitted
                    # prefix pins the exactly-once sequence.
                    self._drop_rails()
                    if (self._resume_fn is None
                            or self.resumes >= max_resumes):
                        raise
                    time.sleep(_retry_backoff(self.resumes))
                    self.resumes += 1
                    self._sid = None
                    self._replica, self._sid_ref = \
                        self._resume_fn(self._emitted)
                    continue
                for item in batch["items"]:
                    self._emitted.append(item)
                    yield item
                if batch["done"]:
                    return
        finally:
            self._drop_rails()
            self._settle()
