"""serve.run / shutdown / handles (ref: python/ray/serve/api.py:537 run)."""
from __future__ import annotations

import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, get_or_create_controller
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle

_proxy_handle = None
_proxy_port: Optional[int] = None


def _update_persisted_routes(mutate) -> None:
    """Read-modify-write the durable route table ("serve"/"routes" in
    the GCS KV): a restarted HTTP proxy — or one started after a
    controller/GCS restart — re-installs routes from here instead of
    coming back empty.  Best-effort: local mode has no KV."""
    import json as _json

    try:
        from ray_tpu.api import _global_worker, is_initialized

        if not is_initialized():
            return
        w = _global_worker()
        blob = w.kv_get("serve", b"routes")
        routes = _json.loads(blob.decode()) if blob else {}
        mutate(routes)
        w.kv_put("serve", b"routes",
                 _json.dumps(routes, sort_keys=True).encode())
    except Exception:  # noqa: BLE001
        pass


def _deploy_one(controller, name: str, dep: Deployment, init_args,
                init_kwargs) -> None:
    cfg = {
        "num_replicas": dep.config.num_replicas,
        "max_ongoing_requests": dep.config.max_ongoing_requests,
        "ray_actor_options": dep.config.ray_actor_options,
        "autoscaling_config": (
            vars(dep.config.autoscaling_config)
            if dep.config.autoscaling_config else None),
    }
    ray_tpu.get(controller.deploy.remote(
        name, dep.func_or_class, init_args, init_kwargs, cfg),
        timeout=60)


def _deploy_graph(controller, app: Application, name: str) -> None:
    """Deployment-graph composition (ref: serve/_private/
    deployment_graph_build.py:1, serve/dag.py): an Application whose
    init args contain OTHER bound Applications is a DAG with `app` as
    the ingress node. Children deploy first (post-order) under
    '{name}#{deployment}' and each graph edge is replaced by a
    DeploymentHandle, so a request to the ingress flows through the
    whole graph via ordinary handle calls."""
    deployed = {}          # id(Application) -> deployed app name
    on_stack = set()       # cycle detection
    used_names = {name}

    def child_name(dep_name: str) -> str:
        base = f"{name}#{dep_name}"
        cand, k = base, 2
        while cand in used_names:
            cand = f"{base}~{k}"
            k += 1
        used_names.add(cand)
        return cand

    def contains_node(v) -> bool:
        if isinstance(v, (Application, Deployment)):
            return True
        if isinstance(v, (list, tuple, set, frozenset)):
            return any(contains_node(x) for x in v)
        if isinstance(v, dict):
            return any(contains_node(x)
                       for kv in v.items() for x in kv)
        return False

    def convert(v):
        # Values with NO graph nodes pass through UNTOUCHED — plain
        # apps (the common path) must not have their defaultdicts/
        # OrderedDicts/custom containers quietly rebuilt as plain types.
        if not contains_node(v):
            return v
        if isinstance(v, Application):
            return DeploymentHandle(deploy_node(v))
        if isinstance(v, Deployment):
            raise TypeError(
                f"deployment {v.name!r} passed unbound into a graph — "
                f"pass {v.name}.bind(...) nodes, not bare Deployments")
        if type(v) in (list, tuple) or hasattr(v, "_fields"):
            vals = [convert(x) for x in v]
            if hasattr(v, "_fields"):       # namedtuple: positional ctor
                return type(v)(*vals)
            return type(v)(vals)
        if type(v) in (set, frozenset):
            return type(v)(convert(x) for x in v)
        if type(v) is dict:
            return {convert(k): convert(x) for k, x in v.items()}
        raise TypeError(
            f"graph nodes inside a {type(v).__name__} init arg are not "
            f"supported — pass bound deployments in plain "
            f"list/tuple/dict/set containers")

    def deploy_node(node: Application) -> str:
        if id(node) in deployed:
            return deployed[id(node)]       # shared node: deploy once
        if id(node) in on_stack:
            raise ValueError("cycle in the deployment graph")
        on_stack.add(id(node))
        try:
            args = tuple(convert(a) for a in node.init_args)
            kwargs = {k: convert(v) for k, v in node.init_kwargs.items()}
        finally:
            on_stack.discard(id(node))
        node_name = (name if node is app
                     else child_name(node.deployment.name))
        _deploy_one(controller, node_name, node.deployment, args, kwargs)
        deployed[id(node)] = node_name
        return node_name

    deploy_node(app)
    # Declarative reconcile: children from a PREVIOUS graph under this
    # name that the new graph no longer contains must not leak replicas.
    try:
        live = ray_tpu.get(controller.list_applications.remote(),
                           timeout=30)
    except Exception:  # noqa: BLE001
        live = []
    for a in live:
        if a.startswith(name + "#") and a not in used_names:
            ray_tpu.get(controller.delete_app.remote(a), timeout=30)


def run(app: Application | Deployment, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _http: bool = False) -> DeploymentHandle:
    """Deploy an application (possibly a graph of bound deployments —
    see _deploy_graph); returns a handle (ref: serve/api.py:537)."""
    if "#" in name:
        raise ValueError(
            f"app name {name!r} may not contain '#' (reserved for "
            f"deployment-graph child namespacing)")
    if isinstance(app, Deployment):
        app = app.bind()
    controller = get_or_create_controller()
    _deploy_graph(controller, app, name)
    # wait for at least one replica
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.app_status.remote(name), timeout=30)
        if st["running"] >= min(1, st["target"]):
            break
        time.sleep(0.1)
    if _http and route_prefix:
        _update_persisted_routes(lambda r: r.__setitem__(route_prefix,
                                                         name))
        # Await route installation: a request racing a fire-and-forget
        # set_route would 404.
        ray_tpu.get(start_http_proxy().set_route.remote(route_prefix, name),
                    timeout=30)
    handle = DeploymentHandle(name)
    if blocking:  # pragma: no cover
        while True:
            time.sleep(1)
    return handle


def _get_or_start_ingress(cached_handle, actor_cls_path: str,
                          actor_name: str, host: str, port: int):
    """Validate a cached detached ingress actor or start a fresh one
    (shared by the HTTP proxy and the native RPC ingress). The cached
    handle may belong to a previous cluster — a driver that shut down
    without serve.shutdown() — so it is pinged before reuse. Returns
    (handle, bound_port)."""
    if cached_handle is not None:
        try:
            return cached_handle, ray_tpu.get(
                cached_handle.port.remote(), timeout=5)
        except Exception:  # noqa: BLE001
            pass
    import importlib

    module, cls_name = actor_cls_path.rsplit(".", 1)
    cls = getattr(importlib.import_module(module), cls_name)
    handle = ray_tpu.remote(cls).options(
        name=actor_name, lifetime="detached",
        max_concurrency=32).remote(host, port)
    return handle, ray_tpu.get(handle.port.remote(), timeout=30)


def start_http_proxy(host: str = "127.0.0.1", port: int = 0):
    """Start (or return) the node's HTTP proxy actor."""
    global _proxy_handle, _proxy_port
    _proxy_handle, _proxy_port = _get_or_start_ingress(
        _proxy_handle, "ray_tpu.serve.http_proxy.HTTPProxy",
        "serve:http_proxy", host, port)
    return _proxy_handle


def http_port() -> Optional[int]:
    return _proxy_port


_rpc_ingress_handle = None
_rpc_ingress_port = None


def start_rpc_ingress(host: str = "127.0.0.1", port: int = 0):
    """Start (or return) the native-protocol ingress actor (ref: the
    gRPC proxy, serve/_private/proxy.py:533 — a binary ingress next to
    HTTP for service-to-service calls)."""
    global _rpc_ingress_handle, _rpc_ingress_port
    _rpc_ingress_handle, _rpc_ingress_port = _get_or_start_ingress(
        _rpc_ingress_handle, "ray_tpu.serve.rpc_ingress.RpcIngress",
        "serve:rpc_ingress", host, port)
    return _rpc_ingress_handle


def rpc_ingress_port() -> Optional[int]:
    return _rpc_ingress_port


def get_deployment_handle(app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name)


def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name)


def status() -> Dict[str, dict]:
    controller = get_or_create_controller()
    apps = ray_tpu.get(controller.list_applications.remote(), timeout=30)
    return {a: ray_tpu.get(controller.app_status.remote(a), timeout=30)
            for a in apps}


def delete(app_name: str) -> None:
    """Delete an app AND its deployment-graph children (named
    '{app}#...')."""
    controller = get_or_create_controller()
    apps = ray_tpu.get(controller.list_applications.remote(), timeout=30)
    doomed = [a for a in apps
              if a == app_name or a.startswith(app_name + "#")]
    # Ingress first: once it is gone no request can route into the
    # children, so their teardown never strands an in-flight call.
    doomed.sort(key=lambda a: (a != app_name, a))
    _update_persisted_routes(
        lambda r: [r.pop(p) for p, a in list(r.items()) if a in doomed])
    if _proxy_handle is not None:
        for a in doomed:
            try:
                ray_tpu.get(_proxy_handle.remove_routes_for.remote(a),
                            timeout=10)
            except Exception:  # noqa: BLE001
                pass
    for a in doomed:
        ray_tpu.get(controller.delete_app.remote(a), timeout=30)


def shutdown() -> None:
    global _proxy_handle, _proxy_port
    global _rpc_ingress_handle, _rpc_ingress_port
    if _proxy_handle is not None:
        try:
            ray_tpu.get(_proxy_handle.stop.remote(), timeout=10)
            ray_tpu.kill(_proxy_handle)
        except Exception:  # noqa: BLE001
            pass
        _proxy_handle = None
        _proxy_port = None
    if _rpc_ingress_handle is not None:
        try:
            ray_tpu.get(_rpc_ingress_handle.stop.remote(), timeout=10)
            ray_tpu.kill(_rpc_ingress_handle)
        except Exception:  # noqa: BLE001
            pass
        _rpc_ingress_handle = None
        _rpc_ingress_port = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass
