"""serve.run / shutdown / handles (ref: python/ray/serve/api.py:537 run)."""
from __future__ import annotations

import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, get_or_create_controller
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle

_proxy_handle = None
_proxy_port: Optional[int] = None


def run(app: Application | Deployment, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _http: bool = False) -> DeploymentHandle:
    """Deploy an application; returns a handle (ref: serve/api.py:537)."""
    if isinstance(app, Deployment):
        app = app.bind()
    dep = app.deployment
    controller = get_or_create_controller()
    cfg = {
        "num_replicas": dep.config.num_replicas,
        "max_ongoing_requests": dep.config.max_ongoing_requests,
        "ray_actor_options": dep.config.ray_actor_options,
        "autoscaling_config": (
            vars(dep.config.autoscaling_config)
            if dep.config.autoscaling_config else None),
    }
    ray_tpu.get(controller.deploy.remote(
        name, dep.func_or_class, app.init_args, app.init_kwargs, cfg),
        timeout=60)
    # wait for at least one replica
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.app_status.remote(name), timeout=30)
        if st["running"] >= min(1, st["target"]):
            break
        time.sleep(0.1)
    if _http and route_prefix:
        # Await route installation: a request racing a fire-and-forget
        # set_route would 404.
        ray_tpu.get(start_http_proxy().set_route.remote(route_prefix, name),
                    timeout=30)
    handle = DeploymentHandle(name)
    if blocking:  # pragma: no cover
        while True:
            time.sleep(1)
    return handle


def _get_or_start_ingress(cached_handle, actor_cls_path: str,
                          actor_name: str, host: str, port: int):
    """Validate a cached detached ingress actor or start a fresh one
    (shared by the HTTP proxy and the native RPC ingress). The cached
    handle may belong to a previous cluster — a driver that shut down
    without serve.shutdown() — so it is pinged before reuse. Returns
    (handle, bound_port)."""
    if cached_handle is not None:
        try:
            return cached_handle, ray_tpu.get(
                cached_handle.port.remote(), timeout=5)
        except Exception:  # noqa: BLE001
            pass
    import importlib

    module, cls_name = actor_cls_path.rsplit(".", 1)
    cls = getattr(importlib.import_module(module), cls_name)
    handle = ray_tpu.remote(cls).options(
        name=actor_name, lifetime="detached",
        max_concurrency=32).remote(host, port)
    return handle, ray_tpu.get(handle.port.remote(), timeout=30)


def start_http_proxy(host: str = "127.0.0.1", port: int = 0):
    """Start (or return) the node's HTTP proxy actor."""
    global _proxy_handle, _proxy_port
    _proxy_handle, _proxy_port = _get_or_start_ingress(
        _proxy_handle, "ray_tpu.serve.http_proxy.HTTPProxy",
        "serve:http_proxy", host, port)
    return _proxy_handle


def http_port() -> Optional[int]:
    return _proxy_port


_rpc_ingress_handle = None
_rpc_ingress_port = None


def start_rpc_ingress(host: str = "127.0.0.1", port: int = 0):
    """Start (or return) the native-protocol ingress actor (ref: the
    gRPC proxy, serve/_private/proxy.py:533 — a binary ingress next to
    HTTP for service-to-service calls)."""
    global _rpc_ingress_handle, _rpc_ingress_port
    _rpc_ingress_handle, _rpc_ingress_port = _get_or_start_ingress(
        _rpc_ingress_handle, "ray_tpu.serve.rpc_ingress.RpcIngress",
        "serve:rpc_ingress", host, port)
    return _rpc_ingress_handle


def rpc_ingress_port() -> Optional[int]:
    return _rpc_ingress_port


def get_deployment_handle(app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name)


def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name)


def status() -> Dict[str, dict]:
    controller = get_or_create_controller()
    apps = ray_tpu.get(controller.list_applications.remote(), timeout=30)
    return {a: ray_tpu.get(controller.app_status.remote(a), timeout=30)
            for a in apps}


def delete(app_name: str) -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_app.remote(app_name), timeout=30)


def shutdown() -> None:
    global _proxy_handle, _proxy_port
    global _rpc_ingress_handle, _rpc_ingress_port
    if _proxy_handle is not None:
        try:
            ray_tpu.get(_proxy_handle.stop.remote(), timeout=10)
            ray_tpu.kill(_proxy_handle)
        except Exception:  # noqa: BLE001
            pass
        _proxy_handle = None
        _proxy_port = None
    if _rpc_ingress_handle is not None:
        try:
            ray_tpu.get(_rpc_ingress_handle.stop.remote(), timeout=10)
            ray_tpu.kill(_rpc_ingress_handle)
        except Exception:  # noqa: BLE001
            pass
        _rpc_ingress_handle = None
        _rpc_ingress_port = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass
