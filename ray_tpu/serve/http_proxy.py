"""HTTP ingress: per-node proxy actor routing to deployment handles.

Reference: uvicorn-based `HTTPProxy` actor per node
(ref: python/ray/serve/_private/proxy.py:747; GenericProxy routing :129).
Stdlib-only equivalent (uvicorn isn't in this image): a ThreadingHTTPServer
inside a proxy actor; JSON bodies in, JSON out; routes by prefix.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict


class HTTPProxy:
    """Actor: owns the HTTP server + route table {prefix: app_name}."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu.serve.handle import DeploymentHandle

        proxy = self
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, DeploymentHandle] = {}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, body):
                path = self.path.split("?")[0].rstrip("/") or "/"
                app = None
                match_len = -1
                for prefix, name in proxy._routes.items():
                    if (path == prefix or path.startswith(
                            prefix.rstrip("/") + "/")) \
                            and len(prefix) > match_len:
                        app, match_len = name, len(prefix)
                if app is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "no route"}')
                    return
                h = proxy._handles.get(app)
                if h is None:
                    h = proxy._handles[app] = DeploymentHandle(app)
                try:
                    arg = json.loads(body) if body else None
                    out = h.remote(arg).result(timeout=60)
                    payload = json.dumps(out).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(payload)
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps(
                        {"error": str(e)}).encode())

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self._dispatch(self.rfile.read(n) if n else None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def port(self) -> int:
        return self._server.server_address[1]

    def set_route(self, prefix: str, app_name: str) -> bool:
        self._routes[prefix] = app_name
        return True

    def remove_route(self, prefix: str) -> bool:
        self._routes.pop(prefix, None)
        return True

    def stop(self) -> bool:
        self._server.shutdown()
        return True
