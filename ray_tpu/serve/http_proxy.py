"""HTTP ingress: async per-node proxy actor routing to deployment handles.

Reference: uvicorn-based `HTTPProxy` actor per node with streaming
responses (ref: python/ray/serve/_private/proxy.py:747; GenericProxy
routing :129). aiohttp replaces uvicorn here: requests are served on the
proxy's own asyncio loop; handle calls (which block on the runtime) run
on an executor pool; streaming deployments answer with chunked JSONL —
one line per yielded item — so token streams reach the client as they
are generated (TTFT == first chunk).

Routes: POST/GET <prefix>            -> unary   {"...": ...}
        POST/GET <prefix>?stream=1   -> chunked JSONL stream
Headers: X-Model-Id (or body {"model_id": ...}) -> multiplexed routing.
"""
from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional


class HTTPProxy:
    """Actor: owns the aiohttp server + route table {prefix: app_name}."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 executor_threads: int = 64):
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, object] = {}
        self._executor = ThreadPoolExecutor(max_workers=executor_threads,
                                            thread_name_prefix="proxy")
        # Separate pool for stream pulls: long-running unary calls must
        # not starve in-flight token streams.
        self._stream_executor = ThreadPoolExecutor(
            max_workers=executor_threads, thread_name_prefix="proxy-stream")
        self._port: Optional[int] = None
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._host = host
        self._want_port = port
        threading.Thread(target=self._serve_thread, daemon=True).start()
        if not self._started.wait(30):
            raise RuntimeError("HTTP proxy failed to start")

    # -- aiohttp server on a dedicated loop -----------------------------
    def _serve_thread(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)

        async def start():
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._want_port)
            await site.start()
            self._port = site._server.sockets[0].getsockname()[1]
            self._started.set()

        loop.run_until_complete(start())
        loop.run_forever()

    def _match_route(self, path: str) -> Optional[str]:
        path = path.rstrip("/") or "/"
        app, match_len = None, -1
        for prefix, name in self._routes.items():
            if (path == prefix
                    or path.startswith(prefix.rstrip("/") + "/")):
                if len(prefix) > match_len:
                    app, match_len = name, len(prefix)
        return app

    def _handle_for(self, app_name: str):
        h = self._handles.get(app_name)
        if h is None:
            from ray_tpu.serve.handle import DeploymentHandle

            h = self._handles[app_name] = DeploymentHandle(app_name)
        return h

    async def _dispatch(self, request):
        from aiohttp import web

        app_name = self._match_route(request.path)
        if app_name is None:
            return web.json_response({"error": "no route"}, status=404)
        body = await request.read()
        try:
            arg = json.loads(body) if body else None
        except ValueError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        model_id = request.headers.get("X-Model-Id") or (
            arg.get("model_id") if isinstance(arg, dict) else None)
        stream = (request.query.get("stream") in ("1", "true")
                  or (isinstance(arg, dict) and arg.get("stream")))

        handle = self._handle_for(app_name)
        method = request.query.get("method") or (
            arg.get("method") if isinstance(arg, dict) else None)
        if model_id or method:
            handle = handle.options(
                multiplexed_model_id=model_id,
                method_name=method)
        loop = asyncio.get_running_loop()

        if not stream:
            try:
                out = await loop.run_in_executor(
                    self._executor,
                    lambda: handle.remote(arg).result(timeout=120))
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": str(e)}, status=500)
            return web.json_response(out)

        # Streaming: chunked JSONL, one line per yielded item. Routing
        # happens BEFORE headers go out so routing failures are clean
        # 500s, not truncated 200s.
        try:
            stream_resp = await loop.run_in_executor(
                self._stream_executor, lambda: handle.remote_streaming(arg))
            it = iter(stream_resp)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)

        resp = web.StreamResponse(headers={
            "Content-Type": "application/jsonl; charset=utf-8"})
        resp.enable_chunked_encoding()
        await resp.prepare(request)

        def pull_next():
            try:
                return next(it), False
            except StopIteration:
                return None, True

        try:
            while True:
                item, done = await loop.run_in_executor(
                    self._stream_executor, pull_next)
                if done:
                    break
                await resp.write(
                    (json.dumps(item) + "\n").encode())
        except Exception as e:  # noqa: BLE001
            # Best-effort error line — the socket may already be gone
            # (client disconnect); the finally still cancels the stream.
            try:
                await resp.write(
                    (json.dumps({"error": str(e)}) + "\n").encode())
            except Exception:  # noqa: BLE001
                pass
        finally:
            stream_resp.cancel()  # idempotent; frees the replica stream
        try:
            await resp.write_eof()
        except Exception:  # noqa: BLE001
            pass
        return resp

    # -- actor RPC surface ----------------------------------------------
    def port(self) -> int:
        return self._port

    def set_route(self, prefix: str, app_name: str) -> bool:
        self._routes[prefix] = app_name
        return True

    def remove_route(self, prefix: str) -> bool:
        self._routes.pop(prefix, None)
        return True

    def stop(self) -> bool:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        return True
