"""HTTP ingress: async per-node proxy actor routing to deployment handles.

Reference: uvicorn-based `HTTPProxy` actor per node with streaming
responses (ref: python/ray/serve/_private/proxy.py:747; GenericProxy
routing :129). aiohttp replaces uvicorn here: requests are served on the
proxy's own asyncio loop; handle calls (which block on the runtime) run
on an executor pool; streaming deployments answer with chunked JSONL —
one line per yielded item — so token streams reach the client as they
are generated (TTFT == first chunk).

Robustness: every request gets an id (X-Request-Id in, generated
otherwise) echoed in error bodies, logs, and the response header;
admission is bounded at RAY_TPU_SERVE_PROXY_MAX_INFLIGHT in-flight
requests — beyond it the proxy SHEDS with 503 + Retry-After instead of
queueing without limit; replica-death/draining failures map to 503 (the
client should retry), client mistakes stay 404/422, and unary calls run
under the RAY_TPU_SERVE_REQUEST_DEADLINE_S deadline.  Mid-stream replica
death is invisible here: the handle's StreamingResponse fails over and
resumes exactly-once underneath the JSONL writer.

Routes: POST/GET <prefix>            -> unary   {"...": ...}
        POST/GET <prefix>?stream=1   -> chunked JSONL stream
Headers: X-Model-Id (or body {"model_id": ...}) -> multiplexed routing.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ray_tpu.util import tracing

logger = logging.getLogger("ray_tpu.serve.http_proxy")


def _error_status(e: BaseException) -> tuple:
    """(status, retryable) for a dispatch failure — 503 + Retry-After
    for transient routing/capacity conditions, 504 for deadline, 500
    otherwise."""
    import ray_tpu.exceptions as rexc

    if isinstance(e, (rexc.ActorDiedError, rexc.ActorUnavailableError,
                      rexc.ReplicaDrainingError, rexc.StreamQueueFullError)):
        return 503, True
    if isinstance(e, (rexc.GetTimeoutError, TimeoutError)):
        return 504, False
    return 500, False


class HTTPProxy:
    """Actor: owns the aiohttp server + route table {prefix: app_name}."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 executor_threads: int = 64,
                 max_inflight: Optional[int] = None,
                 request_deadline_s: Optional[float] = None):
        from ray_tpu.core.config import get_config

        cfg = get_config()
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, object] = {}
        self._max_inflight = (max_inflight if max_inflight is not None
                              else cfg.serve_proxy_max_inflight)
        self._deadline_s = (request_deadline_s
                            if request_deadline_s is not None
                            else cfg.serve_request_deadline_s)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._shed_total = 0
        self._executor = ThreadPoolExecutor(max_workers=executor_threads,
                                            thread_name_prefix="proxy")
        # Separate pool for stream pulls: long-running unary calls must
        # not starve in-flight token streams.
        self._stream_executor = ThreadPoolExecutor(
            max_workers=executor_threads, thread_name_prefix="proxy-stream")
        self._port: Optional[int] = None
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._host = host
        self._want_port = port
        self._load_persisted_routes()
        # The proxy has no gauge loop to piggyback its registry on, so
        # it runs the generic worker->daemon metrics pusher (no-op in
        # local mode).
        from ray_tpu.serve import observability

        self._metrics = observability.metrics()
        self._metrics_push_stop = observability.start_push_loop(
            f"proxy:{os.getpid()}")
        threading.Thread(target=self._serve_thread, daemon=True).start()
        if not self._started.wait(30):
            raise RuntimeError("HTTP proxy failed to start")

    def _load_persisted_routes(self) -> None:
        """A restarted proxy re-installs the route table from the GCS KV
        ("serve"/"routes", written by serve.run) instead of coming back
        empty — routes survive proxy AND controller death, and the GCS
        PersistentStore carries them across GCS restarts."""
        try:
            from ray_tpu.api import _global_worker, is_initialized

            if not is_initialized():
                return
            blob = _global_worker().kv_get("serve", b"routes")
            if blob:
                self._routes.update(json.loads(blob.decode()))
        except Exception:  # noqa: BLE001 best-effort recovery
            pass

    # -- aiohttp server on a dedicated loop -----------------------------
    def _serve_thread(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)

        async def start():
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._want_port)
            await site.start()
            self._port = site._server.sockets[0].getsockname()[1]
            self._started.set()

        loop.run_until_complete(start())
        loop.run_forever()

    def _match_route(self, path: str) -> Optional[str]:
        path = path.rstrip("/") or "/"
        app, match_len = None, -1
        for prefix, name in self._routes.items():
            if (path == prefix
                    or path.startswith(prefix.rstrip("/") + "/")):
                if len(prefix) > match_len:
                    app, match_len = name, len(prefix)
        return app

    def _handle_for(self, app_name: str):
        h = self._handles.get(app_name)
        if h is None:
            from ray_tpu.serve.handle import DeploymentHandle

            h = self._handles[app_name] = DeploymentHandle(app_name)
        return h

    def _error_response(self, e: BaseException, rid: str, path: str):
        from aiohttp import web

        status, retryable = _error_status(e)
        logger.warning("request %s %s failed (%d): %s",
                       rid, path, status, e)
        headers = {"X-Request-Id": rid}
        if retryable:
            headers["Retry-After"] = "1"
        return web.json_response(
            {"error": str(e), "request_id": rid},
            status=status, headers=headers)

    async def _dispatch(self, request):
        from aiohttp import web

        rid = request.headers.get("X-Request-Id") or uuid.uuid4().hex
        app_name = self._match_route(request.path)
        if app_name is None:
            return web.json_response(
                {"error": "no route", "request_id": rid}, status=404,
                headers={"X-Request-Id": rid})
        body = await request.read()
        try:
            arg = json.loads(body) if body else None
        except ValueError:
            return web.json_response(
                {"error": "invalid JSON", "request_id": rid}, status=422,
                headers={"X-Request-Id": rid})
        # Bounded admission: shed beyond max_inflight with an explicit
        # 503 + Retry-After — the proxy stays responsive under overload
        # instead of parking every extra request on a 120 s blocking
        # executor wait.
        with self._inflight_lock:
            if self._inflight >= self._max_inflight:
                self._shed_total += 1
                shed = True
            else:
                self._inflight += 1
                shed = False
        if shed:
            logger.warning("request %s %s shed (inflight >= %d)",
                           rid, request.path, self._max_inflight)
            self._metrics["shed"].inc(1, {"app": app_name})
            self._metrics["requests"].inc(
                1, {"app": app_name, "status": "503"})
            return web.json_response(
                {"error": "overloaded", "request_id": rid}, status=503,
                headers={"Retry-After": "1", "X-Request-Id": rid})
        # The request id IS the trace id: spans from every downstream
        # hop (handle routing, replica, engine ticks) join this trace,
        # and `ray-tpu serve trace <X-Request-Id>` renders the track.
        ctx = tracing.serve_ctx(rid)
        status = "500"
        try:
            with tracing.serve_span(ctx, "serve.proxy.request",
                                    app=app_name,
                                    path=request.path) as s:
                resp = await self._dispatch_admitted(
                    request, arg, app_name, rid,
                    trace=tracing.child_ctx(ctx, s))
                status = str(resp.status)
                if s is not None:
                    s.attrs["status"] = resp.status
                return resp
        finally:
            self._metrics["requests"].inc(
                1, {"app": app_name, "status": status})
            with self._inflight_lock:
                self._inflight -= 1

    async def _dispatch_admitted(self, request, arg, app_name: str,
                                 rid: str, trace: Optional[dict] = None):
        from aiohttp import web

        model_id = request.headers.get("X-Model-Id") or (
            arg.get("model_id") if isinstance(arg, dict) else None)
        stream = (request.query.get("stream") in ("1", "true")
                  or (isinstance(arg, dict) and arg.get("stream")))

        handle = self._handle_for(app_name)
        method = request.query.get("method") or (
            arg.get("method") if isinstance(arg, dict) else None)
        if model_id or method:
            handle = handle.options(
                multiplexed_model_id=model_id,
                method_name=method)
        loop = asyncio.get_running_loop()
        deadline = self._deadline_s

        if not stream:
            try:
                out = await loop.run_in_executor(
                    self._executor,
                    lambda: handle.remote(
                        arg, _request_id=rid, _trace=trace,
                    ).result(timeout=deadline))
            except Exception as e:  # noqa: BLE001
                return self._error_response(e, rid, request.path)
            return web.json_response(out,
                                     headers={"X-Request-Id": rid})

        # Streaming: chunked JSONL, one line per yielded item. Routing
        # happens BEFORE headers go out so routing failures are clean
        # status codes, not truncated 200s.  Mid-stream replica death is
        # handled UNDER this loop by StreamingResponse's resume protocol;
        # only exhausted-failover errors surface here.
        try:
            stream_resp = await loop.run_in_executor(
                self._stream_executor,
                lambda: handle.remote_streaming(
                    arg, _request_id=rid, _trace=trace))
            it = iter(stream_resp)
        except Exception as e:  # noqa: BLE001
            return self._error_response(e, rid, request.path)

        resp = web.StreamResponse(headers={
            "Content-Type": "application/jsonl; charset=utf-8",
            "X-Request-Id": rid})
        resp.enable_chunked_encoding()
        await resp.prepare(request)

        def pull_next():
            try:
                return next(it), False
            except StopIteration:
                return None, True

        n_items = 0
        n_bytes = 0
        t0 = time.time()
        try:
            while True:
                item, done = await loop.run_in_executor(
                    self._stream_executor, pull_next)
                if done:
                    break
                line = (json.dumps(item) + "\n").encode()
                n_items += 1
                n_bytes += len(line)
                await resp.write(line)
        except Exception as e:  # noqa: BLE001
            # Best-effort error line — the socket may already be gone
            # (client disconnect); the finally still cancels the stream.
            logger.warning("stream %s %s aborted: %s",
                           rid, request.path, e)
            try:
                await resp.write(
                    (json.dumps({"error": str(e), "request_id": rid})
                     + "\n").encode())
            except Exception:  # noqa: BLE001
                pass
        finally:
            stream_resp.cancel()  # idempotent; frees the replica stream
            # One span for the whole streamed body (per-batch spans come
            # from the replica's stream_next; this one carries totals +
            # how many failovers the resume protocol absorbed).
            tracing.record_serve_span(
                trace, "serve.proxy.stream", t0, items=n_items,
                bytes=n_bytes, resumes=stream_resp.resumes)
        try:
            await resp.write_eof()
        except Exception:  # noqa: BLE001
            pass
        return resp

    # -- actor RPC surface ----------------------------------------------
    def port(self) -> int:
        return self._port

    def proxy_stats(self) -> dict:
        with self._inflight_lock:
            return {"inflight": self._inflight,
                    "max_inflight": self._max_inflight,
                    "shed_total": self._shed_total}

    def set_route(self, prefix: str, app_name: str) -> bool:
        self._routes[prefix] = app_name
        return True

    def remove_route(self, prefix: str) -> bool:
        self._routes.pop(prefix, None)
        return True

    def remove_routes_for(self, app_name: str) -> bool:
        for prefix, app in list(self._routes.items()):
            if app == app_name:
                self._routes.pop(prefix, None)
        self._handles.pop(app_name, None)
        return True

    def stop(self) -> bool:
        self._metrics_push_stop.set()
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        return True
