"""Serve controller: reconcile target deployment state against reality.

Reference: singleton `ServeController` actor with `DeploymentStateManager`
reconciliation (ref: python/ray/serve/_private/controller.py:84;
deployment_state.py:2397 manager, :1207 per-deployment loop) and
request-based autoscaling (ref: _private/autoscaling_policy.py:12).

Replicas are named detached actors ("serve:<app>:<dep>#<n>") so handles in
any process resolve them through the GCS named-actor registry — that is
this build's long-poll substitute: handles re-list replicas on a version
bump (ref: _private/long_poll.py:173 LongPollHost).
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.replica import Replica

CONTROLLER_NAME = "serve:controller"


def _worker_kv():
    """Best-effort handle to the GCS internal KV (None outside a
    cluster).  Backed by the GCS PersistentStore when the cluster runs
    with gcs_storage_dir, so serve state survives both controller death
    and GCS restart."""
    try:
        from ray_tpu.api import _global_worker, is_initialized

        if not is_initialized():
            return None
        return _global_worker()
    except Exception:  # noqa: BLE001
        return None


class ServeController:
    """Runs inside a detached actor; reconciliation on a background thread."""

    def __init__(self):
        # app name -> target spec
        self._targets: Dict[str, dict] = {}
        # app name -> {"replicas": {replica_name: handle}, "version": int}
        self._state: Dict[str, dict] = {}
        self._lock = threading.RLock()
        self._stop = False
        self._last_scale: Dict[str, float] = {}
        # app -> {handle_id: (ongoing, monotonic ts)} — TTL'd in
        # _autoscale_signal so dead handles stop counting.
        self._handle_stats: Dict[str, Dict[str, tuple]] = {}
        from ray_tpu.core.config import get_config

        self._handle_stats_ttl_s = get_config().serve_autoscale_stats_ttl_s
        # Last syncer-merged per-app replica gauges (None outside a
        # distributed cluster); refreshed once per reconcile tick.
        self._merged_gauges: Optional[Dict[str, dict]] = None
        # Startup bookkeeping: a replica whose constructor is still
        # running (model load + jit compile can take minutes) must not
        # be killed by the health probe — grace until its FIRST
        # successful check (ref: deployment initialization_timeout_s).
        self._started_at: Dict[str, float] = {}
        self._ready: set = set()
        self._startup_grace_s = get_config().serve_startup_grace_s
        self._health_timeout_s = get_config().serve_health_timeout_s
        self._drain_timeout_s = get_config().serve_drain_timeout_s
        # Retiring replica names -> wall deadline.  Entries block actor-
        # name reuse while the draining process may still be alive and
        # keep the name out of routing; they age out after the drain
        # window (the replica self-terminates at its own deadline).
        self._draining: Dict[str, float] = {}
        # Controller failover: a restarted controller rebuilds targets
        # from the GCS KV and ADOPTS still-running replicas instead of
        # redeploying the world.
        self._recover()
        self._thread = threading.Thread(target=self._reconcile_loop,
                                        daemon=True)
        self._thread.start()

    # ---- persistence / recovery (GCS KV, "serve" namespace) ----------
    def _persist_app(self, app_name: str) -> None:
        w = _worker_kv()
        if w is None:
            return
        try:
            import cloudpickle

            spec = self._targets.get(app_name)
            key = b"app:" + app_name.encode()
            if spec is None:
                w.kv_del("serve", key)
            else:
                # cloudpickle: deployment targets are often classes/
                # closures defined in driver scope, not importable names.
                w.kv_put("serve", key, cloudpickle.dumps(spec))
        except Exception:  # noqa: BLE001 persistence is best-effort
            pass

    def _recover(self) -> None:
        w = _worker_kv()
        if w is None:
            return
        try:
            keys = w.kv_keys("serve", b"app:")
        except Exception:  # noqa: BLE001
            return
        import cloudpickle

        for key in keys or []:
            try:
                blob = w.kv_get("serve", key)
                if not blob:
                    continue
                app = key[len(b"app:"):].decode()
                self._targets[app] = cloudpickle.loads(blob)
                self._state[app] = {"replicas": {}, "gens": {},
                                    "version": 0}
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        if not self._targets:
            return
        # Adopt live replicas recorded in the last published status blob:
        # ping each named actor and re-take ownership of the healthy ones
        # (no duplicate replicas); dead ones are replaced by the first
        # reconcile tick.
        try:
            import json as _json

            blob = w.kv_get("serve", b"status")
            status = _json.loads(blob.decode()) if blob else {}
        except Exception:  # noqa: BLE001
            status = {}
        for app, info in status.items():
            st = self._state.get(app)
            if st is None:
                continue
            for name in info.get("replicas", []):
                try:
                    h = ray_tpu.get_actor(name)
                    ray_tpu.get(h.check_health.remote(), timeout=5)
                except Exception:  # noqa: BLE001
                    continue
                try:
                    gen = int(name.rsplit("#g", 1)[1].split("#", 1)[0])
                except (IndexError, ValueError):
                    gen = self._targets[app]["gen"]
                st["replicas"][name] = h
                st["gens"][name] = gen
                self._started_at[name] = time.monotonic()
                self._ready.add(name)
            st["version"] += 1

    # ---- API used by serve.run / handles -----------------------------
    def deploy(self, app_name: str, cls_or_fn, init_args, init_kwargs,
               config: dict) -> bool:
        with self._lock:
            prev = self._targets.get(app_name)
            gen = (prev["gen"] + 1) if prev else 1
            self._targets[app_name] = {
                "target": cls_or_fn, "args": init_args, "kwargs": init_kwargs,
                "config": config,
                "num_replicas": config["num_replicas"],
                "gen": gen,  # bump => rolling replace of old-code replicas
            }
            self._state.setdefault(app_name,
                                   {"replicas": {}, "gens": {}, "version": 0})
            self._state[app_name]["version"] += 1
            self._persist_app(app_name)
        return True

    def delete_app(self, app_name: str) -> bool:
        with self._lock:
            self._targets.pop(app_name, None)
            self._persist_app(app_name)
        return True

    def get_routing(self, app_name: str) -> dict:
        with self._lock:
            st = self._state.get(app_name)
            if st is None:
                return {"version": -1, "replicas": []}
            out: dict = {"version": st["version"],
                         "replicas": list(st["replicas"].keys())}
            # Cluster-wide prefix registry read side: the syncer-merged
            # per-replica state (role + published prefix digests) maps
            # digest -> owning replica for the handle's prefix-affinity
            # routing.  Restricted to CURRENT replicas: a SIGKILLed or
            # retired replica's stale digests never route (belt) even
            # before the daemon's gauge TTL sweeps them (suspenders).
            merged = (self._merged_gauges or {}).get(app_name) or {}
            reps = merged.get("_replicas")
            if isinstance(reps, dict):
                live = set(out["replicas"])
                owners: Dict[str, str] = {}
                roles: Dict[str, str] = {}
                for rid, ent in reps.items():
                    if not isinstance(ent, dict):
                        continue
                    if ent.get("role"):
                        roles[rid] = str(ent["role"])
                    if rid not in live:
                        continue
                    if ent.get("block_size"):
                        out["kv_block_size"] = int(ent["block_size"])
                    for d in ent.get("prefixes") or ():
                        owners[str(d)] = rid
                if owners:
                    out["prefix_owners"] = owners
                if roles:
                    out["roles"] = roles
            return out

    def list_applications(self) -> List[str]:
        with self._lock:
            return list(self._targets)

    def app_status(self, app_name: str) -> dict:
        with self._lock:
            tgt = self._targets.get(app_name)
            st = self._state.get(app_name, {"replicas": {}, "version": 0})
            return {
                "running": len(st["replicas"]),
                # Constructor finished AND passed a health probe — what
                # "can serve a request right now" actually means.
                "ready": sum(1 for n in st["replicas"]
                             if n in self._ready),
                "target": tgt["num_replicas"] if tgt else 0,
                "version": st["version"],
            }

    def record_autoscale_stats(self, app_name: str, ongoing: float,
                               handle_id: Optional[str] = None) -> None:
        """Per-handle outstanding-count report.  Entries are TTL'd: a
        handle that stops reporting (caller exited, process died) ages
        out instead of pinning its last count into the autoscale signal
        forever.  Decisions happen in `_autoscale_tick` on the reconcile
        cadence, not here — one report must not flap the target."""
        with self._lock:
            per_handle = self._handle_stats.setdefault(app_name, {})
            per_handle[handle_id or "_anon"] = (float(ongoing),
                                                time.monotonic())

    def _autoscale_signal(self, app_name: str) -> Optional[float]:
        """Cluster-wide in-flight estimate for one app.  Preferred
        source: the syncer-merged replica gauges (one GCS RPC per tick,
        fetched by the caller) — replica-reported ongoing + engine queue
        depth.  Fallback: the TTL-filtered per-handle reports."""
        merged = (self._merged_gauges or {}).get(app_name)
        if merged and merged.get("replicas"):
            return (merged.get("ongoing", 0.0)
                    + merged.get("queue_depth", 0.0))
        per_handle = self._handle_stats.get(app_name)
        if not per_handle:
            return None
        now = time.monotonic()
        ttl = self._handle_stats_ttl_s
        for hid, (_, ts) in list(per_handle.items()):
            if now - ts > ttl:
                del per_handle[hid]
        if not per_handle:
            return None
        return sum(v for v, _ in per_handle.values())

    def _fetch_merged_gauges(self) -> None:
        """One `Serve.merged` RPC per reconcile tick (the syncer-fed
        view); local mode / standalone keeps the handle fallback."""
        self._merged_gauges = None
        try:
            from ray_tpu.api import _global_worker, is_initialized

            if not is_initialized():
                return
            w = _global_worker()
            gcs = getattr(w, "gcs", None)
            if gcs is None:
                return
            # GCS load attribution: the controller's gauge poll is the
            # "serve-gauges" component, not generic client traffic.
            self._merged_gauges = gcs.call(
                "Serve", "merged", timeout=5,
                _caller=(getattr(w, "node_id", "") or "controller",
                         "serve-gauges"))
        except Exception:  # noqa: BLE001 gauge plane is best-effort
            self._merged_gauges = None

    def _autoscale_tick(self) -> None:
        self._fetch_merged_gauges()
        with self._lock:
            for app_name, tgt in self._targets.items():
                asc = tgt["config"].get("autoscaling_config")
                if not asc:
                    continue
                signal = self._autoscale_signal(app_name)
                if signal is None:
                    continue
                n = max(1, tgt["num_replicas"])
                per = signal / n
                now = time.time()
                last = self._last_scale.get(app_name, 0.0)
                if per > asc["target_ongoing_requests"] \
                        and n < asc["max_replicas"] \
                        and now - last > asc["upscale_delay_s"]:
                    tgt["num_replicas"] = n + 1
                    self._last_scale[app_name] = now
                    self._persist_app(app_name)
                elif per < asc["target_ongoing_requests"] / 2 \
                        and n > asc["min_replicas"] \
                        and now - last > asc["downscale_delay_s"]:
                    tgt["num_replicas"] = n - 1
                    self._last_scale[app_name] = now
                    self._persist_app(app_name)

    def shutdown(self) -> bool:
        self._stop = True
        with self._lock:
            self._targets.clear()
        # Clear persisted serve state: an intentional shutdown must not
        # be resurrected by the next controller's recovery pass.
        w = _worker_kv()
        if w is not None:
            try:
                for key in (w.kv_keys("serve", b"app:") or []):
                    w.kv_del("serve", key)
                w.kv_del("serve", b"routes")
            except Exception:  # noqa: BLE001
                pass
        self._reconcile_once()
        # Publish the now-empty snapshot: the loop exits on _stop, so
        # without this the dashboard would show the dead apps as
        # healthy forever (no controller left to correct the blob).
        self._publish_status()
        return True

    # ---- reconciliation ----------------------------------------------
    def _reconcile_loop(self):
        while not self._stop:
            try:
                self._autoscale_tick()
                self._reconcile_once()
                self._publish_status()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            time.sleep(0.25)

    def _publish_status(self) -> None:
        """Write a compact status blob to the GCS KV ("serve"/"status")
        so out-of-worker observers — the dashboard head, `ray-tpu
        status` — see app health without actor calls into this
        controller (ref: the reference's controller snapshots consumed
        by dashboard/modules/serve). Published only on change."""
        import json as _json

        with self._lock:  # RLock: app_status re-enters safely
            snap = {}
            merged = getattr(self, "_merged_gauges", None) or {}
            for app in self._targets:
                st = self._state.get(app,
                                     {"replicas": {}, "version": 0})
                snap[app] = {**self.app_status(app),
                             "replicas": sorted(st["replicas"])}
                # Observability ride-along: the syncer-fed per-app gauge
                # aggregate (queue depth, active, tokens/s, occupancy)
                # the autoscaler already fetched this tick.
                if merged.get(app):
                    snap[app]["gauges"] = merged[app]
        if snap == getattr(self, "_last_published", None):
            return
        self._last_published = snap
        try:
            from ray_tpu.api import _global_worker

            _global_worker().kv_put(
                "serve", b"status",
                _json.dumps(snap, sort_keys=True).encode())
        except Exception:  # noqa: BLE001 best-effort observability
            pass

    def _reconcile_once(self):
        with self._lock:
            apps = dict(self._state)
            targets = dict(self._targets)
        # Age out drain records once the replica's own deadline (plus
        # slack for the exit itself) has certainly passed — their actor
        # names become reusable again.
        now_wall = time.monotonic()
        for name, dl in list(self._draining.items()):
            if now_wall > dl + 5.0:
                self._draining.pop(name, None)
        RemoteReplica = ray_tpu.remote(Replica)

        for app, st in apps.items():
            tgt = targets.get(app)
            want = tgt["num_replicas"] if tgt else 0
            gen = tgt["gen"] if tgt else 0
            have = dict(st["replicas"])
            gens = dict(st.get("gens", {}))

            def _forget(name):
                have.pop(name, None)
                gens.pop(name, None)
                self._started_at.pop(name, None)
                self._ready.discard(name)

            def _kill(name):
                # Hard stop: health-failed replicas only (a wedged
                # process cannot drain).
                try:
                    ray_tpu.kill(have[name])
                except Exception:  # noqa: BLE001
                    pass
                _forget(name)

            def _retire(name):
                # Graceful drain (downscale / redeploy): the replica
                # stops admission, finishes in-flight streams up to the
                # drain deadline, then exits on its own; routing drops it
                # NOW, and still-attached streams migrate-by-recompute
                # through the handle resume path when it exits.
                handle = have[name]
                self._draining[name] = (time.monotonic()
                                        + self._drain_timeout_s)
                try:
                    handle.drain.remote(self._drain_timeout_s)
                except Exception:  # noqa: BLE001 already dead
                    _kill(name)
                    return
                _forget(name)

            # replace replicas from an older deploy generation (redeploy
            # with new code/args must not leave old-version replicas serving)
            for name in [n for n, g in list(gens.items()) if g != gen]:
                _retire(name)
            # scale down
            while len(have) > want:
                _retire(sorted(have)[-1])
            # scale up (never reuse a name whose draining process may
            # still be alive)
            idx = 0
            while len(have) < want:
                while True:
                    name = f"serve:{app}#g{gen}#{idx}"
                    if name not in have and name not in self._draining:
                        break
                    idx += 1
                opts = dict(tgt["config"].get("ray_actor_options") or {})
                handle = RemoteReplica.options(
                    name=name, lifetime="detached",
                    max_concurrency=tgt["config"]["max_ongoing_requests"],
                    **opts,
                ).remote(tgt["target"], tgt["args"], tgt["kwargs"], name)
                have[name] = handle
                gens[name] = gen
                self._started_at[name] = time.monotonic()
            # health check: starting replicas get grace until their first
            # successful probe; after that a failed probe means dead.
            # Probes run CONCURRENTLY under one shared wall deadline
            # (bounded gather): all refs are submitted first, then
            # collected — one wedged replica costs the tick
            # serve_health_timeout_s total, not timeout x replicas.
            refs = {}
            for name in list(have):
                try:
                    refs[name] = have[name].check_health.remote()
                except Exception:  # noqa: BLE001
                    refs[name] = None
            now = time.monotonic()
            deadline = now + self._health_timeout_s
            for name, ref in refs.items():
                try:
                    if ref is None:
                        raise RuntimeError("health submit failed")
                    ray_tpu.get(ref, timeout=max(
                        0.1, deadline - time.monotonic()))
                    self._ready.add(name)
                except Exception:  # noqa: BLE001
                    still_starting = (
                        name not in self._ready
                        and now - self._started_at.get(name, now)
                        < self._startup_grace_s)
                    if not still_starting:
                        _kill(name)
            with self._lock:
                cur = self._state.setdefault(
                    app, {"replicas": {}, "gens": {}, "version": 0})
                if set(cur["replicas"]) != set(have):
                    cur["version"] += 1
                cur["replicas"] = have
                cur["gens"] = gens
            if not tgt:
                with self._lock:
                    if not self._state[app]["replicas"]:
                        self._state.pop(app, None)


def get_or_create_controller():
    """Find the detached controller actor or start it."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001
        pass
    RemoteController = ray_tpu.remote(ServeController)
    try:
        return RemoteController.options(
            name=CONTROLLER_NAME, lifetime="detached",
            max_concurrency=16).remote()
    except Exception:  # noqa: BLE001  (lost the creation race)
        return ray_tpu.get_actor(CONTROLLER_NAME)
