"""Declarative Serve deployment from a config dict / YAML file.

Analogue of the reference's config-driven deploys (ref: serve/schema.py
ServeDeploySchema + `serve deploy config.yaml` and the REST config the
dashboard serve module accepts). Schema (one app per entry):

    applications:
      - name: summarizer
        import_path: mypkg.app:build        # callable returning an
                                            # Application/Deployment, or
                                            # a Deployment/class itself
        route_prefix: /summarize            # optional (HTTP route)
        args: {...}                         # kwargs for a builder fn
        deployment_config:
          num_replicas: 2
          max_ongoing_requests: 16
          ray_actor_options: {num_cpus: 1}

`deploy_config(path_or_dict)` deploys/updates every listed app (existing
apps reconcile to the new target, reference-style declarative update).
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional, Union


def _resolve_import(path: str) -> Any:
    module_name, _, attr = path.partition(":")
    if not attr:
        module_name, _, attr = path.rpartition(".")
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def _load(source: Union[str, dict]) -> dict:
    if isinstance(source, dict):
        return source
    with open(source) as f:
        text = f.read()
    try:
        import yaml  # type: ignore

        return yaml.safe_load(text)
    except ImportError:
        import json

        return json.loads(text)


def deploy_config(source: Union[str, dict]) -> Dict[str, Any]:
    """Deploy every application in the config; returns {app: handle}.
    Apps with a route_prefix are installed on the HTTP proxy (started on
    demand)."""
    from ray_tpu import serve
    from ray_tpu.serve.deployment import Application, Deployment

    config = _load(source)
    apps: List[dict] = config.get("applications", [])
    if not apps:
        raise ValueError("config has no 'applications' list")
    handles: Dict[str, Any] = {}
    for app_cfg in apps:
        name = app_cfg["name"]
        target = _resolve_import(app_cfg["import_path"])
        args = app_cfg.get("args") or {}
        dep_cfg = app_cfg.get("deployment_config") or {}

        if isinstance(target, (Application, Deployment)):
            obj = target
        elif isinstance(target, type):
            # A plain class: wrap it; `args` become constructor kwargs.
            obj = serve.deployment(target)
        else:
            obj = target(**args)  # builder function
        if isinstance(obj, Deployment) and not isinstance(target, type) \
                and args:
            raise ValueError(
                f"app {name!r}: 'args' are constructor kwargs and only "
                "apply when import_path is a class or a builder "
                "function — pre-bound Deployment/Application targets "
                "already carry their init args")
        if isinstance(obj, Deployment):
            if dep_cfg:
                obj = obj.options(**dep_cfg)
            app = obj.bind(**(args if isinstance(target, type) else {}))
        elif isinstance(obj, Application):
            if dep_cfg:
                app = obj.deployment.options(**dep_cfg).bind(
                    *obj.init_args, **obj.init_kwargs)
            else:
                app = obj
        else:
            raise TypeError(
                f"import_path {app_cfg['import_path']!r} resolved to "
                f"{type(obj).__name__}; expected a Deployment, an "
                f"Application, a class, or a builder returning one")
        route = app_cfg.get("route_prefix")
        handles[name] = serve.run(
            app, name=name, route_prefix=route,
            _http=route is not None)
    return handles
