"""@serve.batch dynamic request batching.

Reference: `@serve.batch` + `_BatchQueue`
(ref: python/ray/serve/batching.py:456, :76): calls accumulate until
max_batch_size or batch_wait_timeout_s, then the wrapped function runs once
on the list and each caller gets its element back.  Sync-callable variant
(our replicas execute in threads, not asyncio).
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Callable, List, Optional


class _Pending:
    __slots__ = ("value", "event", "result", "error")

    def __init__(self, value):
        self.value = value
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = wait_timeout_s
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._flusher: Optional[threading.Timer] = None

    def submit(self, item) -> Any:
        p = _Pending(item)
        flush_now = False
        with self._lock:
            self._queue.append(p)
            if len(self._queue) >= self._max:
                flush_now = True
            elif self._flusher is None:
                self._flusher = threading.Timer(self._wait, self._flush)
                self._flusher.daemon = True
                self._flusher.start()
        if flush_now:
            self._flush()
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def _flush(self):
        with self._lock:
            batch, self._queue = self._queue, []
            if self._flusher is not None:
                self._flusher.cancel()
                self._flusher = None
        if not batch:
            return
        try:
            results = self._fn([p.value for p in batch])
            if len(results) != len(batch):
                raise ValueError(
                    f"batch fn returned {len(results)} results for "
                    f"{len(batch)} inputs")
            for p, r in zip(batch, results):
                p.result = r
        except BaseException as e:  # noqa: BLE001
            for p in batch:
                p.error = e
        finally:
            for p in batch:
                p.event.set()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(list) -> list becomes fn(item) -> item with dynamic
    batching across concurrent callers."""
    def wrap(fn):
        func_queue: list = []  # lazily-created queue for plain functions
        attr = f"__rtpu_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        def inner(*args):
            if len(args) == 2:
                # Method: store the queue on the instance so its lifetime
                # (and the captured self) ends with the instance.
                self_obj, item = args
                q = getattr(self_obj, attr, None)
                if q is None:
                    q = _BatchQueue(
                        functools.partial(fn, self_obj),
                        max_batch_size, batch_wait_timeout_s)
                    setattr(self_obj, attr, q)
            else:
                (item,) = args
                if not func_queue:
                    func_queue.append(_BatchQueue(
                        fn, max_batch_size, batch_wait_timeout_s))
                q = func_queue[0]
            return q.submit(item)

        return inner

    return wrap if _fn is None else wrap(_fn)
