"""Continuous-batching LLM engines for TPU serving.

Two engines share one public surface (generate / generate_stream /
engine_stats):

  LLMEngine       fixed-slot: requests share a fixed pool of contiguous
                  KV-cache slots, prefill admits whole (bucket-padded)
                  prompts, every tick advances ALL active slots with one
                  fused decode burst.  HBM is reserved for worst-case
                  sequence length and concurrency is capped at the slot
                  count.

  PagedLLMEngine  paged/block KV cache: KV lives in a flat pool of
                  fixed-size blocks (models/decoding.py PagedKVCache);
                  each request holds a block table, blocks are allocated
                  on demand (serve/kv_cache.py KVBlockAllocator), shared
                  between requests with a common prompt prefix
                  (refcounted copy-on-write), and long prompts prefill
                  in chunks interleaved with decode bursts so active
                  streams' inter-token latency stays bounded during
                  prefill storms.  Concurrency is bounded by pool
                  occupancy, not slot count.

Use standalone or as a Serve deployment (`LLMDeployment`, paged by
default) — replicas each own an engine; the pow-2 router spreads
requests.
"""
from __future__ import annotations

import math
import queue
import threading
import time
import uuid
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.util import tracing


# Canonical home is the typed error tree (the wire-typed-errors lint
# rule keeps every boundary-crossing error there); re-exported here for
# the historical import path.
from ray_tpu.exceptions import StreamQueueFullError  # noqa: F401


class _Request:
    __slots__ = ("prompt", "max_tokens", "temperature", "out_tokens",
                 "done", "error", "slot", "submitted_at", "first_token_at",
                 "token_q", "dropped", "blocks", "pos", "prefilling",
                 "no_register", "trace", "submitted_wall", "last_emit_wall")

    def __init__(self, prompt, max_tokens, temperature, stream=False):
        from ray_tpu.core.config import get_config

        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.out_tokens: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.slot = -1
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        # Serve trace context ({"trace_id": <request id>, ...}, None when
        # tracing is off) — engine tick spans parent under it.  Wall
        # clocks alongside the perf counters: spans need epoch stamps.
        self.trace: Optional[dict] = None
        self.submitted_wall = time.time()
        self.last_emit_wall: Optional[float] = None
        # Streaming consumers read tokens as the engine emits them.
        # BOUNDED: a consumer that stops reading must not grow replica
        # RSS without limit — at the bound the stream drops with an
        # explicit error (the engine frees the slot/blocks).
        self.token_q: Optional["queue.Queue"] = (
            queue.Queue(maxsize=max(1, get_config().serve_stream_queue_max))
            if stream else None)
        self.dropped = False
        self.blocks: List[int] = []   # paged engine: owned pool blocks
        self.pos = 0                  # paged engine: tokens prefilled
        self.prefilling = True        # paged engine: not yet decoding
        # Resumed contexts embed generated tokens in `prompt` — never
        # publish them as a reusable prompt prefix.
        self.no_register = False

    def emit(self, tok: int) -> None:
        self.out_tokens.append(tok)
        if self.token_q is not None and not self.dropped:
            try:
                self.token_q.put_nowait(tok)
            except queue.Full:
                self.dropped = True
                self.error = StreamQueueFullError(
                    f"stream consumer fell {self.token_q.maxsize} tokens "
                    f"behind; stream dropped "
                    f"(RAY_TPU_SERVE_STREAM_QUEUE_MAX)",
                    queue_max=self.token_q.maxsize)


class _EngineBase:
    """Shared request-facing surface of both engines. Subclasses provide
    `max_len`, `stats`, `_pending_put(req)`, and a background loop that
    completes requests."""

    @staticmethod
    def _resume_ctx(prompt_tokens, max_tokens, resume_tokens):
        """Fold an interrupted stream's already-emitted tokens into the
        admission context.  The resumed request prefills
        `prompt + resume` — the same full-context recompute the paged
        engine's preemption path runs — and generates only the REMAINING
        `max_tokens - len(resume)` tokens, so a failover caller that
        kept the emitted prefix sees an exactly-once token sequence."""
        if not resume_tokens:
            return list(prompt_tokens), max_tokens, False
        ctx = list(prompt_tokens) + list(resume_tokens)
        return ctx, max(0, max_tokens - len(resume_tokens)), True

    def generate(self, prompt_tokens: List[int], *, max_tokens: int = 64,
                 temperature: float = 0.0,
                 timeout: Optional[float] = 300,
                 resume_tokens: Optional[List[int]] = None,
                 trace: Optional[dict] = None) -> List[int]:
        ctx, remaining, resumed = self._resume_ctx(
            prompt_tokens, max_tokens, resume_tokens)
        if len(ctx) >= self.max_len:
            raise ValueError(f"prompt ({len(ctx)}) >= max_len")
        if resumed and remaining == 0:
            return []
        req = _Request(ctx, remaining, temperature)
        req.no_register = resumed
        self._obs_submit(req, trace)
        self.stats["requests"] += 1
        self._pending_put(req)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return req.out_tokens

    def generate_stream(self, prompt_tokens: List[int], *,
                        max_tokens: int = 64, temperature: float = 0.0,
                        timeout: Optional[float] = 300,
                        resume_tokens: Optional[List[int]] = None,
                        trace: Optional[dict] = None):
        """Yield tokens as the engine produces them (TTFT = first yield;
        the continuous-batching loop keeps decoding other slots while the
        consumer reads).  `resume_tokens` re-admits an interrupted
        stream: the engine recomputes KV for prompt+resume and yields
        only the continuation."""
        ctx, remaining, resumed = self._resume_ctx(
            prompt_tokens, max_tokens, resume_tokens)
        if len(ctx) >= self.max_len:
            raise ValueError(f"prompt ({len(ctx)}) >= max_len")
        if resumed and remaining == 0:
            return
        req = _Request(ctx, remaining, temperature, stream=True)
        req.no_register = resumed
        self._obs_submit(req, trace)
        self.stats["requests"] += 1
        self._pending_put(req)
        deadline = time.monotonic() + (timeout or 300)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("generation timed out")
            try:
                tok = req.token_q.get(timeout=min(remaining, 0.25))
            except queue.Empty:
                # A dropped stream may not fit its end sentinel into the
                # full queue — the done event is the fallback signal.
                if req.done.is_set() and req.token_q.empty():
                    if req.error is not None:
                        raise req.error
                    return
                continue
            if tok is None:
                if req.error is not None:
                    raise req.error
                return
            yield tok

    def engine_stats(self) -> Dict[str, Any]:
        s = dict(self.stats)
        s["p_ttft_mean"] = (s["ttft_sum"] / s["completed"]
                            if s["completed"] else None)
        return s

    def shutdown(self):
        self._stop = True
        self._work.set()

    def _finish_request(self, req: "_Request") -> None:
        """Complete one request: stats + stream sentinel + done event."""
        self.stats["completed"] += 1
        if req.first_token_at is not None:
            self.stats["ttft_sum"] += (req.first_token_at
                                       - req.submitted_at)
        if req.token_q is not None:
            try:
                req.token_q.put_nowait(None)  # stream sentinel
            except queue.Full:
                pass  # dropped stream: done event carries the signal
        req.done.set()

    # -- serving observability ------------------------------------------
    # Spans attribute each engine phase (queue_wait / prefill_chunk /
    # decode_burst) to the request's trace; histograms decompose TTFT /
    # ITL per app.  Spans gate on req.trace (None when the
    # RAY_TPU_SERVE_TRACE_ENABLED kill switch is off); histograms record
    # either way.  The app tag is learned lazily from traced requests —
    # standalone engines (bench, unit tests) report under "-".
    _app_hint = "-"

    def _obs_submit(self, req: "_Request",
                    trace: Optional[dict]) -> None:
        # Direct engine use (no proxy/handle upstream) mints its own
        # trace so span coverage — and the overhead the kill switch
        # removes — is identical with and without the HTTP front.
        req.trace = (trace if trace is not None
                     else tracing.serve_ctx(uuid.uuid4().hex))

    def _obs_app(self, req: "_Request") -> str:
        app = req.trace.get("app") if req.trace else None
        if app:
            self._app_hint = app
            return app
        return self._app_hint

    def _obs_admitted(self, req: "_Request") -> None:
        from ray_tpu.serve import observability

        now = time.time()
        tracing.record_serve_span(req.trace, "serve.engine.queue_wait",
                                  req.submitted_wall, now,
                                  tokens=len(req.prompt))
        observability.observe_phase(self._obs_app(req), "queue_wait",
                                    now - req.submitted_wall)

    def _obs_first_token(self, req: "_Request") -> None:
        from ray_tpu.serve import observability

        observability.metrics()["ttft"].observe(
            req.first_token_at - req.submitted_at,
            {"app": self._obs_app(req)})
        req.last_emit_wall = time.time()

    def _obs_prefill(self, req: "_Request", t0: float,
                     n_tokens: int) -> None:
        from ray_tpu.serve import observability

        t1 = time.time()
        tracing.record_serve_span(req.trace, "serve.engine.prefill_chunk",
                                  t0, t1, tokens=n_tokens, pos=req.pos)
        observability.observe_phase(self._obs_app(req), "prefill", t1 - t0)

    def _obs_burst(self, req: "_Request", t0: float, t1: float,
                   n_new: int) -> None:
        """Per fused-burst, per-request: one decode_burst span, one
        decode_step phase sample, and ONE inter-token-latency sample at
        the burst-mean gap (per-token observes would cost more than the
        decode itself at small models)."""
        if n_new <= 0:
            return
        from ray_tpu.serve import observability

        app = self._obs_app(req)
        tracing.record_serve_span(req.trace, "serve.engine.decode_burst",
                                  t0, t1, tokens=n_new)
        observability.observe_phase(app, "decode_step", t1 - t0)
        if req.last_emit_wall is not None and t1 > req.last_emit_wall:
            observability.metrics()["itl"].observe(
                (t1 - req.last_emit_wall) / n_new, {"app": app})
        req.last_emit_wall = t1


class LLMEngine(_EngineBase):
    def __init__(self, cfg, params, *, num_slots: int = 8,
                 max_len: int = 1024, prefill_buckets=(64, 128, 256, 512),
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_burst: int = 8, prefix_cache_size: int = 4,
                 speculation_k: int = 0, speculation_ngram: int = 2,
                 mesh=None):
        import jax

        from ray_tpu.models.decoding import (
            init_cache,
            make_engine_fns,
            make_prefix_cache_fns,
            make_spec_fns,
        )

        self.cfg = cfg
        # self.params is assigned below, after optional tp resharding.
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = tuple(b for b in sorted(prefill_buckets)
                             if b <= max_len)
        self.eos_id = eos_id
        # Burst size: decode ticks fused per device call.  EOS is only
        # checked between bursts, so with an eos_id short bursts trade
        # throughput for less overshoot; without one there is no waste.
        self.max_burst = max(1, max_burst if eos_id is None else
                             min(max_burst, 4))
        self._jax = jax
        self._rng = jax.random.key(seed)
        if mesh is not None:
            # Tensor-parallel serving: params split over the mesh `tp`
            # axis (TP_RULES), KV cache split on its kv-heads axis —
            # the SAME jitted engine programs run unchanged; GSPMD
            # propagates the shardings and inserts the all-reduces
            # after wo/w_down. This is how a model too big for one
            # chip serves: a sharding annotation, not an engine fork.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ray_tpu.models.decoding import cache_shardings
            from ray_tpu.models.transformer import param_logical_axes
            from ray_tpu.parallel.mesh import AXIS_TENSOR
            from ray_tpu.parallel.sharding import (
                TP_RULES,
                param_shardings,
                shard_pytree,
            )

            tp = int(mesh.shape.get(AXIS_TENSOR, 1))
            for dim_name, dim in (("n_kv_heads", cfg.n_kv_heads),
                                  ("n_heads", cfg.n_heads),
                                  ("d_ff", cfg.d_ff),
                                  ("vocab_size", cfg.vocab_size)):
                if dim % tp:
                    raise ValueError(
                        f"tensor parallelism {tp} does not divide "
                        f"{dim_name}={dim} for model {cfg.name!r} — "
                        f"pick a tp that divides all sharded dims")
            shardings = param_shardings(param_logical_axes(cfg), mesh,
                                        TP_RULES)
            # Shard from HOST copies so the unsharded model never has
            # to fit on one chip (pass host arrays from params_loader
            # for models that genuinely don't).
            params = shard_pytree(jax.device_get(params), shardings)
            self.cache = init_cache(cfg, num_slots, max_len,
                                    shardings=cache_shardings(mesh))
            self._rng = jax.device_put(
                self._rng, NamedSharding(mesh, P()))
        else:
            self.cache = init_cache(cfg, num_slots, max_len)
        self.params = params
        self._prefill, self._decode = make_engine_fns(
            cfg, num_slots=num_slots, max_len=max_len)
        # Prefix cache (the vLLM automatic-prefix-caching analogue,
        # scoped to WHOLE prompts): repeated prompts — shared system
        # prompts, retries, bench warmups — skip prefill entirely; a
        # hit costs one HBM slot-write + one sampling call instead of
        # the full prompt forward. LRU-bounded; 0 disables.
        self._prefix_cache_size = max(0, prefix_cache_size)
        # Insertion-ordered dict IS the LRU: re-insert on hit, pop the
        # oldest key on overflow.
        self._prefix_cache: "Dict[tuple, dict]" = {}
        if self._prefix_cache_size:
            (self._px_extract, self._px_insert,
             self._px_sample) = make_prefix_cache_fns()
        # Prompt-lookup speculative decoding (opt-in): each tick
        # verifies K candidate tokens per slot in one call; drafts come
        # from n-gram matches in the slot's own context. Exact under
        # greedy decoding; sampling slots degrade to normal decode.
        self._spec_k = speculation_k if speculation_k >= 2 else 0
        self._spec_ngram = max(1, speculation_ngram)
        # The cache margin _maybe_finish keeps free must cover whichever
        # advance is larger — a burst OR a spec window — WITHOUT
        # inflating the actual burst depth (the EOS-overshoot cap on
        # max_burst stays meaningful).
        self._advance_margin = max(self.max_burst, self._spec_k)
        if self._spec_k:
            self._verify = make_spec_fns(cfg)
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._last_tokens = np.zeros((num_slots,), np.int32)
        self._work = threading.Event()
        self._stop = False
        self._lock = threading.Lock()
        self.stats = {"requests": 0, "tokens_generated": 0,
                      "ttft_sum": 0.0, "completed": 0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "spec_proposed": 0, "spec_accepted": 0}
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _pending_put(self, req: "_Request") -> None:
        self._pending.put(req)
        self._work.set()

    # -- engine loop ----------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_len

    def _free_slot(self) -> int:
        for i, r in enumerate(self._slots):
            if r is None:
                return i
        return -1

    def _admit(self) -> bool:
        import jax.numpy as jnp

        slot = self._free_slot()
        if slot < 0:
            return False
        try:
            req = self._pending.get_nowait()
        except queue.Empty:
            return False
        try:
            self._obs_admitted(req)
            n = len(req.prompt)
            key = tuple(req.prompt)
            entry = (self._prefix_cache.get(key)
                     if self._prefix_cache_size else None)
            if entry is not None:
                # Hit: HBM copy of the snapshotted KV + re-sample the
                # stored last-token logits under THIS request's
                # temperature — no prompt forward at all.
                self.cache = self._px_insert(
                    self.cache, entry["k"], entry["v"],
                    jnp.int32(slot), jnp.int32(n))
                tok, self._rng = self._px_sample(
                    entry["logits"], jnp.float32(req.temperature),
                    self._rng)
                self._prefix_cache[key] = self._prefix_cache.pop(key)
                self.stats["prefix_hits"] += 1
            else:
                t0 = time.time()
                bucket = self._bucket_for(n)
                toks = np.zeros((1, bucket), np.int32)
                toks[0, :n] = req.prompt
                self.cache, tok, last_logits, self._rng = self._prefill(
                    self.params, self.cache, jnp.asarray(toks),
                    jnp.int32(slot), jnp.int32(n),
                    jnp.float32(req.temperature), self._rng)
                self.stats["prefix_misses"] += 1
                self._obs_prefill(req, t0, n)
                if self._prefix_cache_size:
                    # Snapshot only the prompt's bucket worth of KV.
                    k_slice, v_slice = self._px_extract(
                        self.cache, jnp.int32(slot), t=bucket)
                    self._prefix_cache[key] = {
                        "k": k_slice, "v": v_slice,
                        "logits": last_logits}
                    while len(self._prefix_cache) > \
                            self._prefix_cache_size:
                        self._prefix_cache.pop(
                            next(iter(self._prefix_cache)))
            req.first_token_at = time.perf_counter()
            self._obs_first_token(req)
            req.emit(int(tok))
            req.slot = slot
            self._slots[slot] = req
            self._last_tokens[slot] = int(tok)
            self._maybe_finish(slot)
        except BaseException as e:  # noqa: BLE001
            req.error = e
            if req.token_q is not None:
                try:
                    req.token_q.put_nowait(None)
                except queue.Full:
                    pass
            req.done.set()
        return True

    def _maybe_finish(self, slot: int) -> None:
        req = self._slots[slot]
        if req is None:
            return
        tok = req.out_tokens[-1] if req.out_tokens else None
        hit_eos = self.eos_id is not None and tok == self.eos_id
        # Margin of one full advance (burst or spec window) below
        # max_len so a fixed-size tick can never run the cache past
        # its capacity.
        full = (len(req.prompt) + len(req.out_tokens)
                >= self.max_len - 1 - getattr(self, "_advance_margin",
                                              self.max_burst))
        if hit_eos or full or len(req.out_tokens) >= req.max_tokens \
                or req.dropped:
            self._slots[slot] = None
            self._finish_request(req)

    def _spec_tick(self, active_mask, temps) -> bool:
        """One speculative verify tick. Returns False when NO slot has
        a draft (caller falls back to the plain burst — no wasted
        K-wide call). Greedy acceptance is exact; any accidentally-
        accepted padding token is by definition the true greedy
        continuation, so padding needs no masking."""
        import jax.numpy as jnp

        from ray_tpu.models.decoding import ngram_propose

        k = self._spec_k
        cand = np.zeros((self.num_slots, k), np.int32)
        drafted = 0
        greedy_active = 0
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            cand[i, 0] = self._last_tokens[i]
            props = []
            if req.temperature == 0.0:
                greedy_active += 1
                ctx = req.prompt + req.out_tokens
                props = ngram_propose(ctx, k - 1, self._spec_ngram)
            for j in range(1, k):
                cand[i, j] = (props[j - 1] if j - 1 < len(props)
                              else self._last_tokens[i])
            if props:
                drafted += 1
        # Run the verify tick only when a MAJORITY of active greedy
        # slots carry a draft: slots without one (and sampling slots)
        # advance a single token per spec tick, so a lone drafted slot
        # must not preempt the max_burst-deep decode for everyone else.
        total_active = int(active_mask.sum())
        if drafted == 0 or 2 * drafted < greedy_active \
                or 2 * greedy_active < total_active:
            return False
        # All k-1 candidate columns of every GREEDY slot count as
        # proposed — padding (last-token repeats) can legitimately
        # accept too, and accepted must never exceed proposed.
        self.stats["spec_proposed"] += (k - 1) * greedy_active
        self.cache, tok_out, accepted, self._rng = self._verify(
            self.params, self.cache, jnp.asarray(cand),
            jnp.asarray(active_mask), jnp.asarray(temps), self._rng)
        tok_out = np.asarray(tok_out)
        accepted = np.asarray(accepted)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            a = int(accepted[i])
            self.stats["spec_accepted"] += a
            for tok in tok_out[i, :a + 1]:
                tok = int(tok)
                if len(req.out_tokens) >= req.max_tokens:
                    break  # over-generated tail: trim
                req.emit(tok)
                self._last_tokens[i] = tok
                self.stats["tokens_generated"] += 1
                if self.eos_id is not None and tok == self.eos_id:
                    break
            self._maybe_finish(i)
        return True

    def _loop(self):
        import jax.numpy as jnp

        while not self._stop:
            admitted = self._admit()
            active_mask = np.array([r is not None for r in self._slots])
            if not active_mask.any():
                if not admitted:
                    self._work.wait(timeout=0.05)
                    self._work.clear()
                continue
            try:
                temps = np.array(
                    [r.temperature if r else 0.0 for r in self._slots],
                    np.float32)
                if self._spec_k and self._spec_tick(active_mask, temps):
                    continue
                # Fixed burst size: exactly ONE decode executable (compiles
                # are expensive, especially via remote-compile).  Slots that
                # hit max_tokens mid-burst over-generate and are trimmed;
                # cache overflow is prevented by _maybe_finish's margin.
                burst = self.max_burst
                t0 = time.time()
                self.cache, tok_mat, self._rng = self._decode(
                    self.params, self.cache,
                    jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask), jnp.asarray(temps), self._rng,
                    n_steps=burst)
                tok_mat = np.asarray(tok_mat)          # (burst, S)
                t1 = time.time()
                for i, req in enumerate(self._slots):
                    if req is None:
                        continue
                    n0 = len(req.out_tokens)
                    for step in range(burst):
                        tok = int(tok_mat[step, i])
                        if len(req.out_tokens) >= req.max_tokens:
                            break  # over-generated tail: trim
                        req.emit(tok)
                        self._last_tokens[i] = tok
                        self.stats["tokens_generated"] += 1
                        if (self.eos_id is not None
                                and tok == self.eos_id):
                            break
                    self._obs_burst(req, t0, t1, len(req.out_tokens) - n0)
                    self._maybe_finish(i)
            except BaseException as e:  # noqa: BLE001
                for i, req in enumerate(self._slots):
                    if req is not None:
                        req.error = e
                        if req.token_q is not None:
                            try:
                                req.token_q.put_nowait(None)
                            except queue.Full:
                                pass
                        req.done.set()
                        self._slots[i] = None


class PagedLLMEngine(_EngineBase):
    """Paged/block KV-cache engine (the tentpole of ROADMAP item 1).

    Engine tick: [admit waiting requests] -> [one fused decode burst
    over every DECODING slot] -> [one prefill chunk for the oldest
    PREFILLING slot].  Decode never waits for a whole prompt: a
    max-length prompt occupies at most `prefill_chunk` tokens of device
    time per tick, bounding the inter-token latency of active streams.

    Admission: a request needs pool blocks covering its (non-shared)
    prompt remainder.  When the pool can't cover it, the request WAITS
    at the head of the queue (no error) until completions free blocks.
    """

    def __init__(self, cfg, params, *, num_slots: int = 32,
                 max_len: int = 1024, block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_burst: int = 8, prefix_sharing: Optional[bool] = None,
                 speculation_k: Optional[int] = None,
                 speculation_ngram: Optional[int] = None,
                 store=None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.core.config import get_config
        from ray_tpu.models.decoding import (
            init_paged_cache,
            make_paged_engine_fns,
            make_paged_spec_fns,
            sample_one,
        )
        from ray_tpu.serve.kv_cache import KVBlockAllocator

        knobs = get_config()
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.max_len = max_len
        self.block_size = block_size or knobs.kv_block_size
        # Default pool budget == the fixed-slot engine's reservation for
        # the same (num_slots, max_len): equal-HBM comparisons are the
        # bench's apples-to-apples claim.  +1 for the null block.
        self.num_blocks = (num_blocks or knobs.kv_block_count
                           or (num_slots * max_len) // self.block_size + 1)
        self.prefill_chunk = prefill_chunk or knobs.serve_prefill_chunk
        # Shape tiers (power-of-two) keep device work proportional to
        # LOAD, not capacity: a burst over 3 active streams runs at
        # width 4, not num_slots; a 16-token chunk compiles at width 32,
        # not prefill_chunk.  One compile per tier — the same bucket
        # discipline as fixed-engine prefill.
        self._width_tiers = self._tiers(4, num_slots)
        self._chunk_tiers = self._tiers(32, self.prefill_chunk)
        self.eos_id = eos_id
        self.max_burst = max(1, max_burst if eos_id is None else
                             min(max_burst, 4))
        # Prompt-lookup speculative decoding on the paged pool (opt-in,
        # knob-defaulted): each tick verifies K candidates per slot in
        # one width-K call; drafts come from n-gram matches in the
        # slot's own context.  Exact under greedy decoding; sampling
        # slots degrade to normal decode.
        if speculation_k is None:
            speculation_k = knobs.serve_speculation_k
        if speculation_ngram is None:
            speculation_ngram = knobs.serve_speculation_ngram
        self._spec_k = speculation_k if speculation_k >= 2 else 0
        self._spec_ngram = max(1, speculation_ngram)
        # The free-margin _maybe_finish keeps must cover whichever
        # advance is larger — a burst OR a spec window — without
        # inflating the burst depth itself.
        self._advance_margin = max(self.max_burst, self._spec_k)
        self._b_max = math.ceil(max_len / self.block_size)
        prefix_sharing = (knobs.kv_block_prefix_sharing
                          if prefix_sharing is None else prefix_sharing)
        self._jax = jax
        self._jnp = jnp
        self._rng = jax.random.key(seed)
        self.cache = init_paged_cache(cfg, self.num_blocks, self.block_size)
        self._prefill_chunk_fn, self._decode, self._copy_block = \
            make_paged_engine_fns(cfg)
        if self._spec_k:
            self._verify = make_paged_spec_fns(cfg)
        self._sample_one = jax.jit(sample_one)
        bytes_per_block = (2 * cfg.n_layers * self.block_size
                           * cfg.n_kv_heads * cfg.head_dim
                           * jnp.zeros((), cfg.compute_dtype).dtype.itemsize)
        self.allocator = KVBlockAllocator(
            self.num_blocks, self.block_size, store=store,
            bytes_per_block=bytes_per_block if store is not None else 0,
            prefix_sharing=prefix_sharing)
        # Host-side engine state: per-slot block tables + lengths (the
        # compiled step only ever sees fixed (S, B_max) arrays).
        self._tables = np.zeros((num_slots, self._b_max), np.int32)
        self._lengths = np.zeros((num_slots,), np.int32)
        self._last_tokens = np.zeros((num_slots,), np.int32)
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._prefillq: deque = deque()   # slots awaiting prefill chunks
        self._pending: deque = deque()
        self._pending_lock = threading.Lock()
        self._work = threading.Event()
        self._stop = False
        # Serializes whole engine ticks against the foreign-thread KV
        # surface (import_prefix / export_streams): those read and
        # replace self.cache, which a mid-tick decode would otherwise
        # race.  Uncontended cost is one lock per tick.
        self._tick_lock = threading.Lock()
        self.stats = {"requests": 0, "tokens_generated": 0,
                      "ttft_sum": 0.0, "completed": 0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "prefill_chunks": 0, "queue_waits": 0,
                      "preemptions": 0, "adopted_blocks": 0,
                      "migrated_blocks": 0, "migrate_fallbacks": 0,
                      "disagg_prefills": 0,
                      "spec_proposed": 0, "spec_accepted": 0}
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _pending_put(self, req: "_Request") -> None:
        with self._pending_lock:
            self._pending.append(req)
        self._work.set()

    def shutdown(self):
        super().shutdown()
        self._thread.join(timeout=5)
        self.allocator.release()

    def engine_stats(self) -> Dict[str, Any]:
        s = super().engine_stats()
        s.update(self.allocator.snapshot())
        s["queue_depth"] = len(self._pending)
        s["active"] = sum(1 for r in self._slots if r is not None)
        return s

    def warmup(self) -> None:
        """Compile every width/chunk tier up front (benchmarks; serving
        just compiles tiers lazily as load ramps).  Inactive-lane calls
        scatter into the null block — garbage no request reads."""
        import jax.numpy as jnp

        for w in self._width_tiers:
            z = np.zeros((w,), np.int32)
            self.cache, _, self._rng = self._decode(
                self.params, self.cache, jnp.asarray(z),
                jnp.zeros((w, self._b_max), jnp.int32), jnp.asarray(z),
                jnp.zeros((w,), bool), jnp.zeros((w,), jnp.float32),
                self._rng, n_steps=self.max_burst)
            if self._spec_k:
                self.cache, _, _, self._rng = self._verify(
                    self.params, self.cache,
                    jnp.zeros((w, self._spec_k), jnp.int32),
                    jnp.zeros((w, self._b_max), jnp.int32),
                    jnp.asarray(z), jnp.zeros((w,), bool),
                    jnp.zeros((w,), jnp.float32), self._rng)
        for c in self._chunk_tiers:
            self.cache, _ = self._prefill_chunk_fn(
                self.params, self.cache, jnp.zeros((c,), jnp.int32),
                jnp.zeros((self._b_max,), jnp.int32), jnp.int32(0),
                jnp.int32(0))

    def gauges(self) -> Dict[str, float]:
        """Cheap autoscaling signals (riding the syncer push)."""
        snap = self.allocator.snapshot()
        return {"queue_depth": float(len(self._pending)),
                "active": float(sum(1 for r in self._slots
                                    if r is not None)),
                "occupancy": snap["occupancy"]}

    # -- engine loop ----------------------------------------------------
    @staticmethod
    def _tiers(lo: int, hi: int) -> List[int]:
        out = []
        w = lo
        while w < hi:
            out.append(w)
            w *= 2
        out.append(hi)
        return out

    def _tier_for(self, tiers: List[int], n: int) -> int:
        for t in tiers:
            if n <= t:
                return t
        return tiers[-1]

    def _free_slot(self) -> int:
        for i, r in enumerate(self._slots):
            if r is None:
                return i
        return -1

    def _table_row(self, slot: int, blocks: List[int]) -> None:
        self._tables[slot, :] = 0
        self._tables[slot, :len(blocks)] = blocks

    def _admit_one(self) -> bool:
        import jax.numpy as jnp

        slot = self._free_slot()
        if slot < 0:
            return False
        with self._pending_lock:
            req = self._pending[0] if self._pending else None
        if req is None:
            return False
        bs = self.block_size
        n = len(req.prompt)
        shared, covered, meta = self.allocator.lookup_prefix(req.prompt)
        if covered == n and meta is None and shared:
            # Whole-prompt chain without stored logits (evicted): fall
            # back to re-prefilling the tail chunk.
            self.allocator.free(shared[-1:])
            shared = shared[:-1]
            covered = len(shared) * bs
        need = math.ceil(n / bs) - len(shared)
        # Admission wants one burst of decode growth on top of the
        # prompt — cuts (but can't eliminate; preemption is the
        # backstop) admit-then-deadlock on growth blocks.
        headroom = need + math.ceil(self.max_burst / bs)
        alloc = ((self.allocator.alloc(need)
                  if self.allocator.can_alloc(headroom) else None)
                 if need > 0 else [])
        if alloc is None:
            # Pool exhausted: the request WAITS at the queue head (no
            # error); completions free blocks and wake the loop.
            self.allocator.free(shared)
            self.stats["queue_waits"] += 1
            return False
        with self._pending_lock:
            self._pending.popleft()
        self._obs_admitted(req)
        blocks = shared + alloc
        req.blocks = blocks
        req.slot = slot
        req.pos = covered
        self._slots[slot] = req
        self._table_row(slot, blocks)
        self._lengths[slot] = 0
        if covered > 0:
            self.stats["prefix_hits"] += 1
        if covered == n:
            # Whole-prompt hit: sample the first token from the stored
            # last-logits under THIS request's temperature — no prompt
            # forward at all.  COW the (shared) partial tail before
            # decode appends into it.
            try:
                self._cow_tail(req)
                tok, self._rng = self._sample_one(
                    meta, jnp.float32(req.temperature), self._rng)
                self._begin_decode(req, int(tok))
            except BaseException as e:  # noqa: BLE001
                self._fail_request(req, e)
            return True
        if covered == 0:
            self.stats["prefix_misses"] += 1
        self._prefillq.append(slot)
        return True

    def _cow_tail(self, req: "_Request", n_ctx: Optional[int] = None
                  ) -> None:
        """Give `req` an exclusively-owned, writable tail block (device
        copy when the tail is shared or registered)."""
        import jax.numpy as jnp

        n = len(req.prompt) if n_ctx is None else n_ctx
        if n % self.block_size == 0 or not req.blocks:
            return  # aligned: first append allocates a fresh block
        tail = req.blocks[-1]
        new, copied = self.allocator.cow(tail)
        if copied:
            self.cache = self._copy_block(self.cache, jnp.int32(new),
                                          jnp.int32(tail))
            req.blocks[-1] = new
            self._table_row(req.slot, req.blocks)

    def _begin_decode(self, req: "_Request", first_tok: int) -> None:
        # KV written so far = the prefilled context (a preempted request
        # re-enters here with out_tokens already emitted).
        n_ctx = len(req.prompt) + len(req.out_tokens)
        if req.first_token_at is None:
            req.first_token_at = time.perf_counter()
            self._obs_first_token(req)
        req.prefilling = False
        req.emit(first_tok)
        self._last_tokens[req.slot] = first_tok
        self._lengths[req.slot] = n_ctx
        self._maybe_finish(req.slot)

    def _fail_request(self, req: "_Request", e: BaseException) -> None:
        req.error = e
        slot = req.slot
        if 0 <= slot < self.num_slots and self._slots[slot] is req:
            self._slots[slot] = None
            self._tables[slot, :] = 0
        if slot in self._prefillq:
            self._prefillq.remove(slot)
        self.allocator.free(req.blocks)
        req.blocks = []
        if req.token_q is not None:
            try:
                req.token_q.put_nowait(None)
            except queue.Full:
                pass
        req.done.set()

    def _prefill_tick(self) -> bool:
        """Prefill chunks in FIFO order under a TOKEN budget of
        `prefill_chunk` per engine tick: a max-length prompt consumes
        the whole budget in one wide chunk (then yields the device back
        to decode — the ITL bound), while a tickful of short prompts
        batches several narrow chunks into the same budget (admission
        isn't serialized to one prompt per tick)."""
        import jax.numpy as jnp

        budget = self.prefill_chunk
        progressed = False
        while self._prefillq and budget > 0:
            slot = self._prefillq[0]
            req = self._slots[slot]
            if req is None:
                self._prefillq.popleft()
                continue
            try:
                t0 = time.time()
                # Preempted requests re-prefill their WHOLE context —
                # prompt plus the tokens already emitted (the stream
                # keeps every token; only the KV is recomputed).
                ctx = req.prompt + req.out_tokens
                n = len(ctx)
                if not req.blocks:   # preemption freed them: re-alloc
                    # Resume only with one burst of growth headroom on
                    # top of the context — otherwise the resumed
                    # request immediately re-stalls on the blocks it
                    # just freed and ping-pongs with the survivor.
                    bs = self.block_size
                    headroom = math.ceil((n + self.max_burst) / bs)
                    alloc = (self.allocator.alloc(math.ceil(n / bs))
                             if self.allocator.can_alloc(headroom)
                             else None)
                    if alloc is None:
                        self.stats["queue_waits"] += 1
                        break        # wait for completions to free blocks
                    req.blocks = alloc
                    self._table_row(slot, req.blocks)
                nv = min(budget, n - req.pos)
                c = self._tier_for(self._chunk_tiers, nv)
                nv = min(nv, c)
                toks = np.zeros((c,), np.int32)
                toks[:nv] = ctx[req.pos:req.pos + nv]
                self.cache, last_logits = self._prefill_chunk_fn(
                    self.params, self.cache, jnp.asarray(toks),
                    jnp.asarray(self._tables[slot]), jnp.int32(req.pos),
                    jnp.int32(nv))
                req.pos += nv
                budget -= nv
                progressed = True
                self.stats["prefill_chunks"] += 1
                self._obs_prefill(req, t0, nv)
                if req.pos >= n:
                    self._prefillq.popleft()
                    if not req.out_tokens and not req.no_register:
                        # Publish the prompt's blocks for prefix reuse
                        # BEFORE our own appends diverge the tail (COW
                        # keeps the registered copy pristine).  Resumed
                        # contexts contain generated tokens — not
                        # reusable prompts; skip.
                        self.allocator.register_prefix(
                            req.prompt, req.blocks, meta=last_logits)
                    self._cow_tail(req, n)
                    tok, self._rng = self._sample_one(
                        last_logits, jnp.float32(req.temperature),
                        self._rng)
                    self._begin_decode(req, int(tok))
            except BaseException as e:  # noqa: BLE001
                if self._prefillq and self._prefillq[0] == slot:
                    self._prefillq.popleft()
                self._fail_request(req, e)
        return progressed

    def _ensure_blocks(self, req: "_Request", upto: int) -> bool:
        """Extend `req`'s table to cover positions [0, upto) — alloc on
        demand.  False = pool exhausted; the slot sits out this burst
        (it resumes when completions free blocks)."""
        need = math.ceil(upto / self.block_size) - len(req.blocks)
        if need <= 0:
            return True
        alloc = self.allocator.alloc(need)
        if alloc is None:
            return False
        req.blocks.extend(alloc)
        self._table_row(req.slot, req.blocks)
        return True

    def _decode_tick(self) -> bool:
        import jax.numpy as jnp

        burst = self.max_burst
        # One tick advances either a burst (burst tokens of KV) or a
        # spec window (K tokens of KV); cover whichever is larger so
        # the spec/burst choice below never re-runs allocation.
        adv = max(burst, self._spec_k)
        idx: List[int] = []
        stalled: List[int] = []
        for i, req in enumerate(self._slots):
            if req is None or req.prefilling:
                continue
            if self._ensure_blocks(req, int(self._lengths[i]) + adv):
                idx.append(i)
            else:
                stalled.append(i)
        if not idx:
            if len(stalled) >= 2:
                # Deadlock: every decoder needs growth blocks and the
                # pool is exhausted by the decoders themselves — nobody
                # can finish to free blocks.  Preempt the youngest
                # (vLLM-style recompute preemption): its blocks free the
                # others; it re-prefills prompt+emitted later.
                self._preempt(max(stalled,
                                  key=lambda i:
                                  self._slots[i].submitted_at))
            return False
        # Compact the active slots into the smallest width tier: device
        # work tracks the number of LIVE streams, not the configured
        # capacity (a ramp-up tick with 3 decoders runs a width-4 burst,
        # not a num_slots-wide one).  All per-slot state is host-side,
        # so lane mapping is just row selection.
        w = self._tier_for(self._width_tiers, len(idx))
        tokens = np.zeros((w,), np.int32)
        tables = np.zeros((w, self._b_max), np.int32)
        lengths = np.zeros((w,), np.int32)
        active = np.zeros((w,), bool)
        temps = np.zeros((w,), np.float32)
        for j, i in enumerate(idx):
            tokens[j] = self._last_tokens[i]
            tables[j] = self._tables[i]
            lengths[j] = self._lengths[i]
            active[j] = True
            temps[j] = self._slots[i].temperature
        try:
            if self._spec_k and self._spec_tick(idx, tables, lengths,
                                                active, temps):
                return True
            t0 = time.time()
            self.cache, tok_mat, self._rng = self._decode(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(active), jnp.asarray(temps), self._rng,
                n_steps=burst)
            tok_mat = np.asarray(tok_mat)              # (burst, w)
            t1 = time.time()
            for j, i in enumerate(idx):
                req = self._slots[i]
                self._lengths[i] += burst   # KV written for every step
                n0 = len(req.out_tokens)
                for step in range(burst):
                    tok = int(tok_mat[step, j])
                    if len(req.out_tokens) >= req.max_tokens:
                        break  # over-generated tail: trim
                    req.emit(tok)
                    self._last_tokens[i] = tok
                    self.stats["tokens_generated"] += 1
                    if self.eos_id is not None and tok == self.eos_id:
                        break
                self._obs_burst(req, t0, t1, len(req.out_tokens) - n0)
                self._maybe_finish(i)
        except BaseException as e:  # noqa: BLE001
            for i, req in enumerate(self._slots):
                if req is not None:
                    self._fail_request(req, e)
        return True

    def _spec_tick(self, idx: List[int], tables, lengths, active,
                   temps) -> bool:
        """One speculative verify tick over the compacted decode lanes.
        Returns False when too few slots carry a draft (caller falls
        back to the plain burst — no wasted K-wide call); the majority
        rule mirrors the fixed engine's.  Called from inside
        _decode_tick's try block after _ensure_blocks already extended
        every participating table to cover the K window, so the kernel's
        scatter is always in-bounds and always lands in exclusively-
        owned blocks (COW at decode start + fresh growth allocs) —
        rejected drafts are rolled back by length arithmetic alone."""
        import jax.numpy as jnp

        from ray_tpu.models.decoding import ngram_propose

        k = self._spec_k
        w = tables.shape[0]
        cand = np.zeros((w, k), np.int32)
        drafted = 0
        greedy_active = 0
        for j, i in enumerate(idx):
            req = self._slots[i]
            cand[j, 0] = self._last_tokens[i]
            props = []
            if req.temperature == 0.0:
                greedy_active += 1
                ctx = req.prompt + req.out_tokens
                props = ngram_propose(ctx, k - 1, self._spec_ngram)
            for col in range(1, k):
                cand[j, col] = (props[col - 1] if col - 1 < len(props)
                                else self._last_tokens[i])
            if props:
                drafted += 1
        if drafted == 0 or 2 * drafted < greedy_active \
                or 2 * greedy_active < len(idx):
            return False
        self.stats["spec_proposed"] += (k - 1) * greedy_active
        t0 = time.time()
        self.cache, tok_out, accepted, self._rng = self._verify(
            self.params, self.cache, jnp.asarray(cand),
            jnp.asarray(tables), jnp.asarray(lengths),
            jnp.asarray(active), jnp.asarray(temps), self._rng)
        tok_out = np.asarray(tok_out)              # (w, k)
        accepted = np.asarray(accepted)            # (w,)
        t1 = time.time()
        for j, i in enumerate(idx):
            req = self._slots[i]
            a = int(accepted[j])
            self.stats["spec_accepted"] += a
            # KV was written for the whole K window; only a+1 positions
            # are real.  Advancing lengths by a+1 IS the rollback: the
            # paged masks (kv_pos <= position) treat the stale tail as
            # garbage and the next decode overwrites it in place.
            self._lengths[i] += a + 1
            n0 = len(req.out_tokens)
            for tok in tok_out[j, :a + 1]:
                tok = int(tok)
                if len(req.out_tokens) >= req.max_tokens:
                    break  # over-generated tail: trim
                req.emit(tok)
                self._last_tokens[i] = tok
                self.stats["tokens_generated"] += 1
                if self.eos_id is not None and tok == self.eos_id:
                    break
            self._obs_burst(req, t0, t1, len(req.out_tokens) - n0)
            self._maybe_finish(i)
        return True

    def _preempt(self, slot: int) -> None:
        """Evict a stalled decoder: free its blocks (unblocking the
        others) and queue it for full-context re-prefill.  The stream
        keeps every emitted token — only KV is recomputed."""
        req = self._slots[slot]
        self.allocator.free(req.blocks)
        req.blocks = []
        self._tables[slot, :] = 0
        self._lengths[slot] = 0
        req.pos = 0
        req.prefilling = True
        self._prefillq.append(slot)
        self.stats["preemptions"] += 1

    def _maybe_finish(self, slot: int) -> None:
        req = self._slots[slot]
        if req is None:
            return
        tok = req.out_tokens[-1] if req.out_tokens else None
        hit_eos = self.eos_id is not None and tok == self.eos_id
        full = (len(req.prompt) + len(req.out_tokens)
                >= self.max_len - 1 - self._advance_margin)
        if hit_eos or full or len(req.out_tokens) >= req.max_tokens \
                or req.dropped:
            self._slots[slot] = None
            self._tables[slot, :] = 0
            self.allocator.free(req.blocks)
            req.blocks = []
            self._finish_request(req)
            self._work.set()   # freed blocks may unblock the queue head

    def _loop(self):
        while not self._stop:
            with self._tick_lock:
                progressed = False
                # Admit as many waiting requests as slots + blocks allow.
                while self._admit_one():
                    progressed = True
                progressed |= self._decode_tick()
                progressed |= self._prefill_tick()
            if not progressed:
                self._work.wait(timeout=0.02)
                self._work.clear()

    # -- disaggregated serving / live migration -------------------------
    def import_prefix(self, tokens: List[int], kv, block_size: int,
                      last_logits=None) -> int:
        """Adopt a KV frame computed by ANOTHER engine (a dedicated
        prefill actor's handoff, or a draining replica's live-migration
        export) into this engine's block pool: allocate blocks, scatter
        the frame on-device, register the prefix, park the blocks
        cached-free.  The next admission of a prompt starting with
        ``tokens`` walks the ordinary prefix-hit path — zero recompute.

        Returns the number of blocks imported; 0 when the frame can't
        be adopted (geometry mismatch, pool exhausted, sharing off) —
        the caller falls back to recompute.  Thread-safe against the
        engine loop (tick lock)."""
        import numpy as np

        from ray_tpu.models.decoding import scatter_blocks

        kv = np.asarray(kv)
        n_need = -(-len(tokens) // self.block_size)
        if (block_size != self.block_size or kv.ndim != 6
                or kv.shape[0] != 2
                or kv.shape[1:] != (self.cfg.n_layers, kv.shape[2],
                                    self.block_size, self.cfg.n_kv_heads,
                                    self.cfg.head_dim)
                or kv.shape[2] < n_need):
            return 0
        meta = (self._jnp.asarray(last_logits)
                if last_logits is not None else None)
        with self._tick_lock:
            blocks = self.allocator.adopt(tokens, meta=meta)
            if blocks is None:
                return 0
            self.cache = scatter_blocks(self.cache, blocks,
                                        kv[:, :, :len(blocks)])
            # Our allocation reference retires; registered blocks park
            # cached-free with contents intact, exactly like a finished
            # request's published prefix.
            self.allocator.free(blocks)
            return len(blocks)

    def export_streams(self) -> List[Dict[str, Any]]:
        """Snapshot every in-flight DECODING stream as a migration
        ticket: the context tokens whose KV is already written (the
        last emitted token's KV is pending as the next decode input, so
        it stays out) plus the device frame of the covering blocks.
        The receiving engine `import_prefix`s the frame and the
        handle's resume protocol re-admits prompt+emitted — which then
        prefix-hits the imported chain and recomputes at most one
        partial block instead of the whole context.  Exact KV roundtrip
        keeps a greedy stream's continuation byte-identical to never
        having moved."""
        import jax
        import numpy as np

        from ray_tpu.models.decoding import gather_blocks

        out: List[Dict[str, Any]] = []
        bs = self.block_size
        with self._tick_lock:
            for i, req in enumerate(self._slots):
                if req is None or req.prefilling or req.token_q is None:
                    continue
                rid = (req.trace or {}).get("trace_id")
                if not rid:
                    continue  # untraceable: recompute fallback applies
                n_kv = int(self._lengths[i])
                ctx = req.prompt + req.out_tokens
                n_kv = min(n_kv, len(ctx))
                nb = min(len(req.blocks), -(-n_kv // bs)) if n_kv else 0
                if nb <= 0:
                    continue
                frame = np.asarray(jax.device_get(
                    gather_blocks(self.cache, req.blocks[:nb])))
                out.append({"request_id": rid,
                            "tokens": list(ctx[:n_kv]),
                            "block_size": bs, "kv": frame})
        return out


def dryrun_tp_serving(cfg, tp: int, *, timeout: float = 45.0) -> None:
    """Compile-and-run check for tensor-parallel serving on the current
    devices (the serving analogue of parallel.pipeline.dryrun_pipeline;
    the driver's multichip dry-run calls this). The short timeout keeps
    a stalled sharded compile failing INSIDE an external ~60s budget
    with a clear error rather than an opaque external kill."""
    import jax

    from ray_tpu.models import init_params
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(tp=tp, fsdp=1),
                      devices=jax.devices()[:tp])
    eng = LLMEngine(cfg, init_params(jax.random.key(1), cfg),
                    num_slots=2, max_len=64, prefill_buckets=(16,),
                    prefix_cache_size=0, mesh=mesh)
    try:
        out = eng.generate([1, 2, 3], max_tokens=4, timeout=timeout)
        assert len(out) == 4, out
    finally:
        eng.shutdown()


class LLMDeployment:
    """Serve-deployable wrapper: __call__({"tokens": [...], ...}) →
    {"tokens": [...]}.  Build with serve.deployment(LLMDeployment).bind(...).

    `engine="paged"` (default) serves through the paged KV-cache engine;
    `engine="fixed"` is DEPRECATED explicit opt-in to the fixed-slot
    engine (emits a DeprecationWarning — the paged engine covers its
    whole feature set at equal HBM, including speculative decoding).
    Tensor-parallel deployments still fall back to the fixed engine
    without a warning (the paged kernels are single-device for now)."""

    def __init__(self, cfg_name, *, engine: str = "paged",
                 num_slots: int = 8, max_len: int = 512, seed: int = 0,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache_size: int = 4,
                 speculation_k: Optional[int] = None,
                 tensor_parallel: int = 0,
                 prefix_sharing: Optional[bool] = None,
                 disagg: Optional[bool] = None,
                 params_loader: Optional[Callable] = None):
        """`cfg_name`: a registry name (ray_tpu.models.configs) or a
        TransformerConfig instance — e.g. the config half of
        `ray_tpu.models.from_hf(...)`, with `params_loader` returning
        the converted weights (serve real HF checkpoints)."""
        import jax

        from ray_tpu.models import TransformerConfig, configs, init_params

        cfg = (cfg_name if isinstance(cfg_name, TransformerConfig)
               else configs.get(cfg_name))
        params = (params_loader() if params_loader
                  else init_params(jax.random.key(seed), cfg))
        mesh = None
        if tensor_parallel > 1:
            # Claim N local chips as a tp mesh for this replica (the
            # router still spreads requests across replicas).
            # build_mesh permutes devices so the tp axis sits on
            # contiguous ICI neighborhoods — exactly where per-token
            # all-reduces must live.
            from ray_tpu.parallel.mesh import MeshConfig, build_mesh

            devs = jax.devices()[:tensor_parallel]
            if len(devs) < tensor_parallel:
                raise ValueError(
                    f"tensor_parallel={tensor_parallel} > "
                    f"{len(jax.devices())} visible devices")
            mesh = build_mesh(MeshConfig(tp=tensor_parallel, fsdp=1),
                              devices=devs)
            engine = "fixed"
        elif engine == "fixed":
            warnings.warn(
                "LLMDeployment(engine='fixed') is deprecated: the paged "
                "engine is the default and covers the fixed engine's "
                "feature set (prefix caching, speculative decoding) at "
                "equal HBM with block-granular sharing. The fixed "
                "engine remains only as the tensor-parallel fallback "
                "and for explicit opt-in.",
                DeprecationWarning, stacklevel=2)
        if engine == "paged":
            store = None
            try:
                import ray_tpu.api as _api

                if _api.is_initialized():
                    store = getattr(_api._global_worker(), "store", None)
            except Exception:  # noqa: BLE001 standalone use
                store = None
            self.engine = PagedLLMEngine(
                cfg, params, num_slots=num_slots, max_len=max_len,
                block_size=block_size, num_blocks=num_blocks,
                prefill_chunk=prefill_chunk, seed=seed,
                prefix_sharing=prefix_sharing,
                speculation_k=speculation_k, store=store)
        else:
            self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                    max_len=max_len,
                                    prefix_cache_size=prefix_cache_size,
                                    speculation_k=speculation_k or 0,
                                    mesh=mesh)
        # Disaggregated serving: this replica decodes; chunked prefill
        # of long prompts offloads to dedicated prefill actors whose
        # finished KV blocks ship back as frames (serve/disagg.py).
        from ray_tpu.core.config import get_config

        if disagg is None:
            disagg = get_config().serve_disagg_enabled
        self._disagg = None
        self.disagg_role = "unified"
        # Prefill actors re-derive weights from (cfg, seed); a custom
        # params_loader would hand them different weights than this
        # replica decodes with — KV frames would silently mismatch.
        if disagg and engine == "paged" and params_loader is None:
            from ray_tpu.serve.disagg import DisaggPrefillClient

            self._disagg = DisaggPrefillClient(
                cfg_name=cfg_name, seed=seed,
                block_size=self.engine.block_size,
                max_len=max_len)
            self.disagg_role = "decode"

    def set_serve_context(self, app: str, replica_id: str) -> None:
        """Replica-actor hook: lets the disagg client tag its prefill
        actors' gauge pushes with the hosting app."""
        if self._disagg is not None:
            self._disagg.set_serve_context(app, replica_id)

    def _maybe_offload_prefill(self, tokens,
                               trace: Optional[dict] = None) -> None:
        """Disagg hot path: a long prompt whose KV this replica doesn't
        already hold prefills on a dedicated prefill actor; the finished
        blocks ship back as a frame and import into the local pool, so
        the engine's own admission sees a whole-prompt prefix hit and
        the decode loop never runs the long prefill chunks.  Any
        failure (actor down, pool full) degrades to local prefill."""
        if self._disagg is None:
            return
        t0 = time.time()
        try:
            offloaded = self._disagg.prefill_into(self.engine,
                                                  list(tokens))
        except Exception:  # noqa: BLE001 degrade to local prefill
            return
        if offloaded:
            tracing.record_serve_span(trace, "serve.prefill.offload",
                                      t0, time.time(),
                                      tokens=len(tokens))

    def __call__(self, request: dict,
                 _serve_trace: Optional[dict] = None) -> dict:
        self._maybe_offload_prefill(request["tokens"],
                                    trace=_serve_trace)
        toks = self.engine.generate(
            request["tokens"],
            max_tokens=int(request.get("max_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            trace=_serve_trace)
        return {"tokens": toks}

    def stream(self, request: dict, _serve_resume: Optional[dict] = None,
               _serve_trace: Optional[dict] = None):
        """Streaming entry: yields {"token": t} dicts (served over
        chunked HTTP by the proxy; call via handle.remote_streaming).

        `_serve_resume` is the replica-injected failover context
        ({"offset": n, "items": [...]}): the tokens a dead replica
        already delivered are re-admitted through the engine's recompute
        path (resume_tokens) so this replica yields only the
        continuation — no duplicated or re-generated tokens."""
        resume = [it["token"] for it in (_serve_resume or {}).get(
            "items", []) if isinstance(it, dict) and "token" in it]
        if not resume:
            self._maybe_offload_prefill(request["tokens"])
        for tok in self.engine.generate_stream(
                request["tokens"],
                max_tokens=int(request.get("max_tokens", 32)),
                temperature=float(request.get("temperature", 0.0)),
                resume_tokens=resume or None,
                trace=_serve_trace):
            yield {"token": tok}

    def stats(self, _request: Optional[dict] = None) -> dict:
        return self.engine.engine_stats()

    def serve_state(self) -> dict:
        """Replica gauge-loop hook: disagg role + the digests of this
        engine's registered (aligned) prefixes.  Rides the existing
        report_serve_gauges/syncer push into the GCS-resident prefix
        registry (no new RPC plane); the handle's prefix-affinity
        routing reads the merged owner map back out of controller
        routing state."""
        from ray_tpu.core.config import get_config

        cfg = get_config()
        state: dict = {"role": self.disagg_role}
        es = self.engine.engine_stats()
        if es.get("spec_proposed"):
            state["spec_accept_rate"] = round(
                es.get("spec_accepted", 0) / es["spec_proposed"], 4)
        alloc = getattr(self.engine, "allocator", None)
        if alloc is not None and cfg.serve_prefix_registry_enabled:
            state["block_size"] = int(self.engine.block_size)
            state["prefixes"] = alloc.prefix_digests(
                limit=cfg.serve_prefix_registry_max_entries)
        return state

    def adopt_kv(self, tokens, kv, block_size: int, last_logits=None,
                 source: str = "migrate") -> int:
        """Import a shipped KV frame (migration ticket / disagg handoff)
        into the hosted engine's pool.  Raises KVMigrationError when the
        engine can't adopt it — the caller's recompute fallback takes
        over.  Returns the number of blocks imported."""
        from ray_tpu.exceptions import KVMigrationError

        imp = getattr(self.engine, "import_prefix", None)
        if imp is None:
            raise KVMigrationError(
                reason="engine has no paged block pool to adopt into")
        n = imp(tokens, kv, block_size, last_logits=last_logits)
        if n <= 0:
            raise KVMigrationError(
                reason=f"import_prefix rejected frame "
                       f"({len(tokens)} tokens, block_size "
                       f"{block_size})")
        key = ("migrated_blocks" if source == "migrate"
               else "adopted_blocks")
        self.engine.stats[key] += n
        return n

    def engine_gauges(self) -> dict:
        """Replica gauge hook: the Replica actor piggybacks these on the
        node daemon's syncer push (serve autoscaling input)."""
        g = getattr(self.engine, "gauges", None)
        if g is not None:
            return g()
        s = self.engine.engine_stats()
        return {"queue_depth": 0.0,
                "active": float(s.get("requests", 0)
                                - s.get("completed", 0)),
                "occupancy": 0.0}
