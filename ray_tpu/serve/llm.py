"""Continuous-batching LLM engine for TPU serving.

The north-star Serve workload (BASELINE.json: "Serve req/s + p50 TTFT",
continuous batching).  Requests share a fixed pool of KV-cache slots:
prefill admits one request into a free slot (bucketed prompt padding keeps
the compile set small); every engine tick advances ALL active slots one
token with a single fused `decode_step`.  Admission interleaves with
decoding — new requests don't wait for the batch to drain (continuous, not
static, batching).

Use standalone (`LLMEngine`) or as a Serve deployment (`LLMDeployment`) —
replicas each own an engine; the pow-2 router spreads requests.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class _Request:
    __slots__ = ("prompt", "max_tokens", "temperature", "out_tokens",
                 "done", "error", "slot", "submitted_at", "first_token_at",
                 "token_q")

    def __init__(self, prompt, max_tokens, temperature, stream=False):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.out_tokens: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.slot = -1
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        # Streaming consumers read tokens as the engine emits them.
        self.token_q: Optional["queue.Queue"] = (
            queue.Queue() if stream else None)

    def emit(self, tok: int) -> None:
        self.out_tokens.append(tok)
        if self.token_q is not None:
            self.token_q.put(tok)


class LLMEngine:
    def __init__(self, cfg, params, *, num_slots: int = 8,
                 max_len: int = 1024, prefill_buckets=(64, 128, 256, 512),
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_burst: int = 8, prefix_cache_size: int = 4,
                 speculation_k: int = 0, speculation_ngram: int = 2,
                 mesh=None):
        import jax

        from ray_tpu.models.decoding import (
            init_cache,
            make_engine_fns,
            make_prefix_cache_fns,
            make_spec_fns,
        )

        self.cfg = cfg
        # self.params is assigned below, after optional tp resharding.
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = tuple(b for b in sorted(prefill_buckets)
                             if b <= max_len)
        self.eos_id = eos_id
        # Burst size: decode ticks fused per device call.  EOS is only
        # checked between bursts, so with an eos_id short bursts trade
        # throughput for less overshoot; without one there is no waste.
        self.max_burst = max(1, max_burst if eos_id is None else
                             min(max_burst, 4))
        self._jax = jax
        self._rng = jax.random.key(seed)
        if mesh is not None:
            # Tensor-parallel serving: params split over the mesh `tp`
            # axis (TP_RULES), KV cache split on its kv-heads axis —
            # the SAME jitted engine programs run unchanged; GSPMD
            # propagates the shardings and inserts the all-reduces
            # after wo/w_down. This is how a model too big for one
            # chip serves: a sharding annotation, not an engine fork.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ray_tpu.models.decoding import cache_shardings
            from ray_tpu.models.transformer import param_logical_axes
            from ray_tpu.parallel.mesh import AXIS_TENSOR
            from ray_tpu.parallel.sharding import (
                TP_RULES,
                param_shardings,
                shard_pytree,
            )

            tp = int(mesh.shape.get(AXIS_TENSOR, 1))
            for dim_name, dim in (("n_kv_heads", cfg.n_kv_heads),
                                  ("n_heads", cfg.n_heads),
                                  ("d_ff", cfg.d_ff),
                                  ("vocab_size", cfg.vocab_size)):
                if dim % tp:
                    raise ValueError(
                        f"tensor parallelism {tp} does not divide "
                        f"{dim_name}={dim} for model {cfg.name!r} — "
                        f"pick a tp that divides all sharded dims")
            shardings = param_shardings(param_logical_axes(cfg), mesh,
                                        TP_RULES)
            # Shard from HOST copies so the unsharded model never has
            # to fit on one chip (pass host arrays from params_loader
            # for models that genuinely don't).
            params = shard_pytree(jax.device_get(params), shardings)
            self.cache = init_cache(cfg, num_slots, max_len,
                                    shardings=cache_shardings(mesh))
            self._rng = jax.device_put(
                self._rng, NamedSharding(mesh, P()))
        else:
            self.cache = init_cache(cfg, num_slots, max_len)
        self.params = params
        self._prefill, self._decode = make_engine_fns(
            cfg, num_slots=num_slots, max_len=max_len)
        # Prefix cache (the vLLM automatic-prefix-caching analogue,
        # scoped to WHOLE prompts): repeated prompts — shared system
        # prompts, retries, bench warmups — skip prefill entirely; a
        # hit costs one HBM slot-write + one sampling call instead of
        # the full prompt forward. LRU-bounded; 0 disables.
        self._prefix_cache_size = max(0, prefix_cache_size)
        # Insertion-ordered dict IS the LRU: re-insert on hit, pop the
        # oldest key on overflow.
        self._prefix_cache: "Dict[tuple, dict]" = {}
        if self._prefix_cache_size:
            (self._px_extract, self._px_insert,
             self._px_sample) = make_prefix_cache_fns()
        # Prompt-lookup speculative decoding (opt-in): each tick
        # verifies K candidate tokens per slot in one call; drafts come
        # from n-gram matches in the slot's own context. Exact under
        # greedy decoding; sampling slots degrade to normal decode.
        self._spec_k = speculation_k if speculation_k >= 2 else 0
        self._spec_ngram = max(1, speculation_ngram)
        # The cache margin _maybe_finish keeps free must cover whichever
        # advance is larger — a burst OR a spec window — WITHOUT
        # inflating the actual burst depth (the EOS-overshoot cap on
        # max_burst stays meaningful).
        self._advance_margin = max(self.max_burst, self._spec_k)
        if self._spec_k:
            self._verify = make_spec_fns(cfg)
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._last_tokens = np.zeros((num_slots,), np.int32)
        self._work = threading.Event()
        self._stop = False
        self._lock = threading.Lock()
        self.stats = {"requests": 0, "tokens_generated": 0,
                      "ttft_sum": 0.0, "completed": 0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "spec_proposed": 0, "spec_accepted": 0}
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- public ---------------------------------------------------------
    def generate(self, prompt_tokens: List[int], *, max_tokens: int = 64,
                 temperature: float = 0.0,
                 timeout: Optional[float] = 300) -> List[int]:
        if len(prompt_tokens) >= self.max_len:
            raise ValueError(f"prompt ({len(prompt_tokens)}) >= max_len")
        req = _Request(list(prompt_tokens), max_tokens, temperature)
        self.stats["requests"] += 1
        self._pending.put(req)
        self._work.set()
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return req.out_tokens

    def generate_stream(self, prompt_tokens: List[int], *,
                        max_tokens: int = 64, temperature: float = 0.0,
                        timeout: Optional[float] = 300):
        """Yield tokens as the engine produces them (TTFT = first yield;
        the continuous-batching loop keeps decoding other slots while the
        consumer reads)."""
        if len(prompt_tokens) >= self.max_len:
            raise ValueError(f"prompt ({len(prompt_tokens)}) >= max_len")
        req = _Request(list(prompt_tokens), max_tokens, temperature,
                       stream=True)
        self.stats["requests"] += 1
        self._pending.put(req)
        self._work.set()
        deadline = time.monotonic() + (timeout or 300)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("generation timed out")
            try:
                tok = req.token_q.get(timeout=remaining)
            except queue.Empty:
                raise TimeoutError("generation timed out") from None
            if tok is None:
                if req.error is not None:
                    raise req.error
                return
            yield tok

    def engine_stats(self) -> Dict[str, Any]:
        s = dict(self.stats)
        s["p_ttft_mean"] = (s["ttft_sum"] / s["completed"]
                            if s["completed"] else None)
        return s

    def shutdown(self):
        self._stop = True
        self._work.set()

    # -- engine loop ----------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_len

    def _free_slot(self) -> int:
        for i, r in enumerate(self._slots):
            if r is None:
                return i
        return -1

    def _admit(self) -> bool:
        import jax.numpy as jnp

        slot = self._free_slot()
        if slot < 0:
            return False
        try:
            req = self._pending.get_nowait()
        except queue.Empty:
            return False
        try:
            n = len(req.prompt)
            key = tuple(req.prompt)
            entry = (self._prefix_cache.get(key)
                     if self._prefix_cache_size else None)
            if entry is not None:
                # Hit: HBM copy of the snapshotted KV + re-sample the
                # stored last-token logits under THIS request's
                # temperature — no prompt forward at all.
                self.cache = self._px_insert(
                    self.cache, entry["k"], entry["v"],
                    jnp.int32(slot), jnp.int32(n))
                tok, self._rng = self._px_sample(
                    entry["logits"], jnp.float32(req.temperature),
                    self._rng)
                self._prefix_cache[key] = self._prefix_cache.pop(key)
                self.stats["prefix_hits"] += 1
            else:
                bucket = self._bucket_for(n)
                toks = np.zeros((1, bucket), np.int32)
                toks[0, :n] = req.prompt
                self.cache, tok, last_logits, self._rng = self._prefill(
                    self.params, self.cache, jnp.asarray(toks),
                    jnp.int32(slot), jnp.int32(n),
                    jnp.float32(req.temperature), self._rng)
                self.stats["prefix_misses"] += 1
                if self._prefix_cache_size:
                    # Snapshot only the prompt's bucket worth of KV.
                    k_slice, v_slice = self._px_extract(
                        self.cache, jnp.int32(slot), t=bucket)
                    self._prefix_cache[key] = {
                        "k": k_slice, "v": v_slice,
                        "logits": last_logits}
                    while len(self._prefix_cache) > \
                            self._prefix_cache_size:
                        self._prefix_cache.pop(
                            next(iter(self._prefix_cache)))
            req.first_token_at = time.perf_counter()
            req.emit(int(tok))
            req.slot = slot
            self._slots[slot] = req
            self._last_tokens[slot] = int(tok)
            self._maybe_finish(slot)
        except BaseException as e:  # noqa: BLE001
            req.error = e
            if req.token_q is not None:
                req.token_q.put(None)
            req.done.set()
        return True

    def _maybe_finish(self, slot: int) -> None:
        req = self._slots[slot]
        if req is None:
            return
        tok = req.out_tokens[-1] if req.out_tokens else None
        hit_eos = self.eos_id is not None and tok == self.eos_id
        # Margin of one full advance (burst or spec window) below
        # max_len so a fixed-size tick can never run the cache past
        # its capacity.
        full = (len(req.prompt) + len(req.out_tokens)
                >= self.max_len - 1 - getattr(self, "_advance_margin",
                                              self.max_burst))
        if hit_eos or full or len(req.out_tokens) >= req.max_tokens:
            self.stats["completed"] += 1
            self.stats["ttft_sum"] += (req.first_token_at
                                       - req.submitted_at)
            self._slots[slot] = None
            if req.token_q is not None:
                req.token_q.put(None)  # stream sentinel
            req.done.set()

    def _spec_tick(self, active_mask, temps) -> bool:
        """One speculative verify tick. Returns False when NO slot has
        a draft (caller falls back to the plain burst — no wasted
        K-wide call). Greedy acceptance is exact; any accidentally-
        accepted padding token is by definition the true greedy
        continuation, so padding needs no masking."""
        import jax.numpy as jnp

        from ray_tpu.models.decoding import ngram_propose

        k = self._spec_k
        cand = np.zeros((self.num_slots, k), np.int32)
        drafted = 0
        greedy_active = 0
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            cand[i, 0] = self._last_tokens[i]
            props = []
            if req.temperature == 0.0:
                greedy_active += 1
                ctx = req.prompt + req.out_tokens
                props = ngram_propose(ctx, k - 1, self._spec_ngram)
            for j in range(1, k):
                cand[i, j] = (props[j - 1] if j - 1 < len(props)
                              else self._last_tokens[i])
            if props:
                drafted += 1
        # Run the verify tick only when a MAJORITY of active greedy
        # slots carry a draft: slots without one (and sampling slots)
        # advance a single token per spec tick, so a lone drafted slot
        # must not preempt the max_burst-deep decode for everyone else.
        total_active = int(active_mask.sum())
        if drafted == 0 or 2 * drafted < greedy_active \
                or 2 * greedy_active < total_active:
            return False
        # All k-1 candidate columns of every GREEDY slot count as
        # proposed — padding (last-token repeats) can legitimately
        # accept too, and accepted must never exceed proposed.
        self.stats["spec_proposed"] += (k - 1) * greedy_active
        self.cache, tok_out, accepted, self._rng = self._verify(
            self.params, self.cache, jnp.asarray(cand),
            jnp.asarray(active_mask), jnp.asarray(temps), self._rng)
        tok_out = np.asarray(tok_out)
        accepted = np.asarray(accepted)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            a = int(accepted[i])
            self.stats["spec_accepted"] += a
            for tok in tok_out[i, :a + 1]:
                tok = int(tok)
                if len(req.out_tokens) >= req.max_tokens:
                    break  # over-generated tail: trim
                req.emit(tok)
                self._last_tokens[i] = tok
                self.stats["tokens_generated"] += 1
                if self.eos_id is not None and tok == self.eos_id:
                    break
            self._maybe_finish(i)
        return True

    def _loop(self):
        import jax.numpy as jnp

        while not self._stop:
            admitted = self._admit()
            active_mask = np.array([r is not None for r in self._slots])
            if not active_mask.any():
                if not admitted:
                    self._work.wait(timeout=0.05)
                    self._work.clear()
                continue
            try:
                temps = np.array(
                    [r.temperature if r else 0.0 for r in self._slots],
                    np.float32)
                if self._spec_k and self._spec_tick(active_mask, temps):
                    continue
                # Fixed burst size: exactly ONE decode executable (compiles
                # are expensive, especially via remote-compile).  Slots that
                # hit max_tokens mid-burst over-generate and are trimmed;
                # cache overflow is prevented by _maybe_finish's margin.
                burst = self.max_burst
                self.cache, tok_mat, self._rng = self._decode(
                    self.params, self.cache,
                    jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask), jnp.asarray(temps), self._rng,
                    n_steps=burst)
                tok_mat = np.asarray(tok_mat)          # (burst, S)
                for i, req in enumerate(self._slots):
                    if req is None:
                        continue
                    for step in range(burst):
                        tok = int(tok_mat[step, i])
                        if len(req.out_tokens) >= req.max_tokens:
                            break  # over-generated tail: trim
                        req.emit(tok)
                        self._last_tokens[i] = tok
                        self.stats["tokens_generated"] += 1
                        if (self.eos_id is not None
                                and tok == self.eos_id):
                            break
                    self._maybe_finish(i)
            except BaseException as e:  # noqa: BLE001
                for i, req in enumerate(self._slots):
                    if req is not None:
                        req.error = e
                        if req.token_q is not None:
                            req.token_q.put(None)
                        req.done.set()
                        self._slots[i] = None


def dryrun_tp_serving(cfg, tp: int, *, timeout: float = 45.0) -> None:
    """Compile-and-run check for tensor-parallel serving on the current
    devices (the serving analogue of parallel.pipeline.dryrun_pipeline;
    the driver's multichip dry-run calls this). The short timeout keeps
    a stalled sharded compile failing INSIDE an external ~60s budget
    with a clear error rather than an opaque external kill."""
    import jax

    from ray_tpu.models import init_params
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(tp=tp, fsdp=1),
                      devices=jax.devices()[:tp])
    eng = LLMEngine(cfg, init_params(jax.random.key(1), cfg),
                    num_slots=2, max_len=64, prefill_buckets=(16,),
                    prefix_cache_size=0, mesh=mesh)
    try:
        out = eng.generate([1, 2, 3], max_tokens=4, timeout=timeout)
        assert len(out) == 4, out
    finally:
        eng.shutdown()


class LLMDeployment:
    """Serve-deployable wrapper: __call__({"tokens": [...], ...}) →
    {"tokens": [...]}.  Build with serve.deployment(LLMDeployment).bind(...)."""

    def __init__(self, cfg_name, *, num_slots: int = 8,
                 max_len: int = 512, seed: int = 0,
                 prefix_cache_size: int = 4, speculation_k: int = 0,
                 tensor_parallel: int = 0,
                 params_loader: Optional[Callable] = None):
        """`cfg_name`: a registry name (ray_tpu.models.configs) or a
        TransformerConfig instance — e.g. the config half of
        `ray_tpu.models.from_hf(...)`, with `params_loader` returning
        the converted weights (serve real HF checkpoints)."""
        import jax

        from ray_tpu.models import TransformerConfig, configs, init_params

        cfg = (cfg_name if isinstance(cfg_name, TransformerConfig)
               else configs.get(cfg_name))
        params = (params_loader() if params_loader
                  else init_params(jax.random.key(seed), cfg))
        mesh = None
        if tensor_parallel > 1:
            # Claim N local chips as a tp mesh for this replica (the
            # router still spreads requests across replicas).
            # build_mesh permutes devices so the tp axis sits on
            # contiguous ICI neighborhoods — exactly where per-token
            # all-reduces must live.
            from ray_tpu.parallel.mesh import MeshConfig, build_mesh

            devs = jax.devices()[:tensor_parallel]
            if len(devs) < tensor_parallel:
                raise ValueError(
                    f"tensor_parallel={tensor_parallel} > "
                    f"{len(jax.devices())} visible devices")
            mesh = build_mesh(MeshConfig(tp=tensor_parallel, fsdp=1),
                              devices=devs)
        self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                max_len=max_len,
                                prefix_cache_size=prefix_cache_size,
                                speculation_k=speculation_k, mesh=mesh)

    def __call__(self, request: dict) -> dict:
        toks = self.engine.generate(
            request["tokens"],
            max_tokens=int(request.get("max_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)))
        return {"tokens": toks}

    def stream(self, request: dict):
        """Streaming entry: yields {"token": t} dicts (served over
        chunked HTTP by the proxy; call via handle.remote_streaming)."""
        for tok in self.engine.generate_stream(
                request["tokens"],
                max_tokens=int(request.get("max_tokens", 32)),
                temperature=float(request.get("temperature", 0.0))):
            yield {"token": tok}

    def stats(self) -> dict:
        return self.engine.engine_stats()
