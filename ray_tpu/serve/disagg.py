"""Disaggregated serving plane: prefill/decode split, prefix registry
digests, live-KV migration tickets.

Three coordination pieces over machinery that already exists:

  Prefill/decode split   `PrefillWorker` actors run `paged_prefill_chunk`
                         over chunked long prompts in a private block
                         pool and hand the finished blocks back as ONE
                         host frame (models/decoding.py gather_blocks).
                         The decode replica `import_prefix`es the frame
                         into its own pool — a sealed KV block is just
                         bytes riding the zero-copy transfer plane, so
                         the handoff is an object-store put/get, not a
                         new RPC protocol.  Long-prompt prefill stops
                         competing with decode bursts for the decode
                         engine's device time (the long-TTFT vs
                         short-ITL interference the split removes).

  Prefix registry        Replicas publish the digests of their
                         registered block-aligned prefixes through the
                         existing report_serve_gauges -> syncer -> GCS
                         path (TTL-swept with the gauges themselves, so
                         a SIGKILLed replica's entries age out in
                         serve_gauge_ttl_s).  The controller folds the
                         merged owner map into routing state; the
                         handle routes prefix-warm requests to the
                         replica already holding those blocks
                         (serve/handle.py, modeled on multiplexed model
                         affinity).

  Live KV migration      A draining replica exports each in-flight
                         stream's written KV as a ticket (engine
                         export_streams) keyed by request id in the GCS
                         KV "serve" namespace; the handle's resume
                         protocol re-admits the stream on a survivor,
                         whose replica consumes the ticket and
                         import_prefix`es the frame — the resumed
                         context prefix-hits the imported chain and
                         recomputes at most one partial block instead
                         of the whole prompt+emitted recompute.  Any
                         failure anywhere falls back to the PR-9
                         recompute path (exactly-once either way).
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve.kv_cache import prefix_digest

# GCS KV key prefix for migration tickets ("serve" namespace, beside
# the controller's app:*/routes/status keys).
_TICKET_PREFIX = b"migrate:"


def request_digests(tokens, block_size: int,
                    max_bounds: int = 8) -> List[tuple]:
    """(covered_tokens, digest) pairs for a request's block-aligned
    prefix boundaries, LONGEST first — the handle probes these against
    the cluster owner map and routes to the deepest match.  Bounded to
    the last `max_bounds` boundaries so routing cost stays O(1)-ish for
    very long prompts."""
    n_full = len(tokens) // block_size
    bounds = range(max(1, n_full - max_bounds + 1), n_full + 1)
    return [(k * block_size, prefix_digest(tokens[:k * block_size]))
            for k in reversed(list(bounds))] if n_full else []


def _worker():
    try:
        from ray_tpu.api import _global_worker, is_initialized

        if not is_initialized():
            return None
        return _global_worker()
    except Exception:  # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# migration tickets (GCS KV, "serve" namespace)
# ---------------------------------------------------------------------------
def publish_migration_tickets(replica_id: str,
                              tickets: List[Dict[str, Any]]) -> int:
    """Write one GCS-KV ticket per exported stream.  Frames above the
    inline bound are dropped (their streams take the recompute
    fallback) — the KV plane is a small-value store, and a ticket that
    can't be written must not stall the drain."""
    import numpy as np

    from ray_tpu.core.config import get_config

    w = _worker()
    if w is None:
        return 0
    bound = get_config().serve_kv_migrate_inline_max_bytes
    published = 0
    for t in tickets:
        kv = np.ascontiguousarray(t["kv"])
        if kv.nbytes > bound:
            continue
        blob = pickle.dumps({
            "tokens": list(t["tokens"]),
            "block_size": int(t["block_size"]),
            "kv_bytes": kv.tobytes(),
            "kv_shape": kv.shape,
            "kv_dtype": str(kv.dtype),
            "replica": replica_id,
            "ts": time.time(),
        })
        t0 = time.time()
        try:
            w.kv_put("serve", _TICKET_PREFIX
                     + t["request_id"].encode(), blob)
            published += 1
        except Exception:  # noqa: BLE001 fallback: recompute
            continue
        from ray_tpu.util import tracing

        tracing.record_serve_span(
            tracing.serve_ctx(t["request_id"]), "serve.kv.migrate",
            t0, time.time(), side="publish", replica=replica_id,
            nbytes=kv.nbytes, tokens=len(t["tokens"]))
    return published


def consume_migration_ticket(request_id: str) -> Optional[Dict[str, Any]]:
    """Fetch-and-delete the migration ticket for a resumed request
    (at-most-once adopt; stale tickets past the TTL are dropped so a
    re-deployed app never imports last week's KV)."""
    import numpy as np

    from ray_tpu.core.config import get_config

    w = _worker()
    if w is None:
        return None
    key = _TICKET_PREFIX + str(request_id).encode()
    try:
        blob = w.kv_get("serve", key)
    except Exception:  # noqa: BLE001
        return None
    if not blob:
        return None
    try:
        w.kv_del("serve", key)
    except Exception:  # noqa: BLE001 best-effort delete
        pass
    try:
        t = pickle.loads(blob)
        if time.time() - t.get("ts", 0) > \
                get_config().serve_kv_migrate_ttl_s:
            return None
        t["kv"] = np.frombuffer(
            t.pop("kv_bytes"), dtype=t.pop("kv_dtype")
        ).reshape(t.pop("kv_shape"))
        return t
    except Exception:  # noqa: BLE001 corrupt ticket: recompute
        return None


# ---------------------------------------------------------------------------
# prefill actors
# ---------------------------------------------------------------------------
class PrefillWorker:
    """Dedicated prefill actor: chunked `paged_prefill_chunk` over a
    private single-request block pool, returning the finished blocks as
    one transferable frame.  No decode loop, no allocator — the pool is
    exactly one prompt deep, so the actor's whole device time goes to
    prefill throughput (the point of the split)."""

    def __init__(self, cfg_name, *, seed: int = 0,
                 block_size: Optional[int] = None, max_len: int = 1024,
                 prefill_chunk: Optional[int] = None, app: str = "-"):
        import jax
        import numpy as np

        from ray_tpu.core.config import get_config
        from ray_tpu.models import TransformerConfig, configs, init_params
        from ray_tpu.models.decoding import (
            init_paged_cache,
            make_paged_engine_fns,
        )

        knobs = get_config()
        cfg = (cfg_name if isinstance(cfg_name, TransformerConfig)
               else configs.get(cfg_name))
        self.cfg = cfg
        self.params = init_params(jax.random.key(seed), cfg)
        self.block_size = block_size or knobs.kv_block_size
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk or knobs.serve_prefill_chunk
        self._b_max = -(-max_len // self.block_size)
        # Block 0 stays the null block; 1.._b_max is the working set.
        self.cache = init_paged_cache(cfg, self._b_max + 1,
                                      self.block_size)
        self._chunk_fn, _, _ = make_paged_engine_fns(cfg)
        self._np = np
        self._jax = jax
        self._app = app
        self._ongoing = 0
        self.stats = {"prefills": 0, "tokens_prefilled": 0,
                      "chunks": 0}
        self._gauge_stop = threading.Event()
        threading.Thread(target=self._gauge_loop, daemon=True).start()

    def _gauge_loop(self, period_s: float = 1.0) -> None:
        """Surface this actor in the serve gauge plane with
        role=prefill so `ray-tpu serve status` shows the split; the
        same TTL sweep that retires dead replicas retires us."""
        import os

        name = f"serve:{self._app}#prefill#{os.getpid()}"
        while not self._gauge_stop.wait(period_s):
            try:
                w = _worker()
                daemon = getattr(w, "daemon", None) if w else None
                if daemon is None:
                    return
                daemon.call(
                    "NodeDaemon", "report_serve_gauges",
                    app=self._app, replica=name,
                    gauges={"ongoing": float(self._ongoing),
                            "prefills": float(self.stats["prefills"])},
                    state={"role": "prefill"}, timeout=2)
            except Exception:  # noqa: BLE001 best-effort telemetry
                continue

    def prefill(self, tokens: List[int]) -> Dict[str, Any]:
        """Chunked prefill of one prompt; returns the KV frame + the
        last-token logits (the decode side stores them as prefix meta,
        so a whole-prompt hit samples its first token with no forward
        at all)."""
        import jax.numpy as jnp

        from ray_tpu.models.decoding import gather_blocks

        np = self._np
        n = len(tokens)
        if n == 0 or n > self.max_len:
            raise ValueError(f"prompt length {n} outside (0, "
                             f"{self.max_len}]")
        self._ongoing += 1
        try:
            bs = self.block_size
            nb = -(-n // bs)
            blocks = list(range(1, nb + 1))
            table = np.zeros((self._b_max,), np.int32)
            table[:nb] = blocks
            pos = 0
            last = None
            while pos < n:
                nv = min(self.prefill_chunk, n - pos)
                chunk = np.zeros((self.prefill_chunk,), np.int32)
                chunk[:nv] = tokens[pos:pos + nv]
                self.cache, last = self._chunk_fn(
                    self.params, self.cache, jnp.asarray(chunk),
                    jnp.asarray(table), jnp.int32(pos), jnp.int32(nv))
                pos += nv
                self.stats["chunks"] += 1
            frame = np.asarray(self._jax.device_get(
                gather_blocks(self.cache, blocks)))
            self.stats["prefills"] += 1
            self.stats["tokens_prefilled"] += n
            return {"tokens": list(tokens), "block_size": bs,
                    "kv": frame,
                    "last_logits": np.asarray(
                        self._jax.device_get(last))}
        finally:
            self._ongoing -= 1

    def check_health(self) -> bool:
        return True

    def getpid(self) -> int:
        import os

        return os.getpid()


class DisaggPrefillClient:
    """Decode-replica-side client for the prefill pool: lazily creates
    (or attaches to) the named detached PrefillWorker actors and
    offloads long prompts, importing the returned frames into the local
    engine.  Prompt->actor assignment hashes the first block's digest,
    so repeated prompts with a shared system prefix land on the same
    prefill actor (its jitted chunk tiers stay warm)."""

    def __init__(self, cfg_name, *, seed: int, block_size: int,
                 max_len: int):
        self._cfg_name = cfg_name
        self._seed = seed
        self._block_size = block_size
        self._max_len = max_len
        self._actors: Optional[list] = None
        self._lock = threading.Lock()
        self._app = "-"

    def set_serve_context(self, app: str, replica_id: str) -> None:
        self._app = app

    def _pool_key(self) -> str:
        name = getattr(self._cfg_name, "name", None) or \
            (self._cfg_name if isinstance(self._cfg_name, str)
             else "custom")
        return f"{name}-{self._block_size}-{self._max_len}"

    def _ensure_actors(self) -> list:
        import ray_tpu
        from ray_tpu.core.config import get_config

        with self._lock:
            if self._actors is not None:
                return self._actors
            n = max(1, get_config().serve_disagg_prefill_actors)
            actors = []
            RemoteWorker = ray_tpu.remote(PrefillWorker)
            for i in range(n):
                name = f"serve:prefill:{self._pool_key()}#{i}"
                try:
                    actors.append(ray_tpu.get_actor(name))
                    continue
                except Exception:  # noqa: BLE001 not created yet
                    pass
                try:
                    actors.append(RemoteWorker.options(
                        name=name, lifetime="detached").remote(
                        self._cfg_name, seed=self._seed,
                        block_size=self._block_size,
                        max_len=self._max_len, app=self._app))
                except Exception:  # noqa: BLE001 lost creation race
                    actors.append(ray_tpu.get_actor(name))
            self._actors = actors
            return actors

    def prefill_into(self, engine, tokens: List[int]) -> bool:
        """Offload `tokens` to a prefill actor and adopt the frame.
        True when the engine now holds KV covering the whole prompt
        (either freshly imported or already registered); False means
        the caller prefills locally."""
        import ray_tpu
        from ray_tpu.core.config import get_config

        knobs = get_config()
        if len(tokens) < knobs.serve_disagg_prompt_threshold:
            return False
        if len(tokens) > self._max_len:
            return False
        alloc = getattr(engine, "allocator", None)
        if alloc is None or not alloc.prefix_sharing:
            return False
        # Already warm locally (registry hit routed us here, or a
        # previous request published it): nothing to ship.
        held, covered, _meta = alloc.lookup_prefix(tokens)
        alloc.free(held)
        if covered >= len(tokens):
            return True
        actors = self._ensure_actors()
        pick = actors[int(prefix_digest(
            tokens[:self._block_size]), 16) % len(actors)]
        out = ray_tpu.get(pick.prefill.remote(list(tokens)),
                          timeout=knobs.serve_request_deadline_s)
        n = engine.import_prefix(out["tokens"], out["kv"],
                                 out["block_size"],
                                 last_logits=out.get("last_logits"))
        if n <= 0:
            return False
        engine.stats["disagg_prefills"] += 1
        engine.stats["adopted_blocks"] += n
        return True
