"""ray_tpu.serve: online inference (reference: ray.serve).

Controller-reconciled replica sets as named detached actors, power-of-two
request routing, dynamic batching, HTTP ingress, request autoscaling.
"""
from ray_tpu.serve.api import (delete, get_app_handle, get_deployment_handle,
                               http_port, rpc_ingress_port, run, shutdown,
                               start_http_proxy, start_rpc_ingress,
                               status)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config_deploy import deploy_config
from ray_tpu.serve.deployment import (Application, AutoscalingConfig,
                                      Deployment, deployment)
from ray_tpu.serve.handle import (DeploymentHandle, DeploymentResponse,
                                  StreamingResponse)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "deployment", "Deployment", "Application", "AutoscalingConfig",
    "run", "shutdown", "status", "delete",
    "get_deployment_handle", "get_app_handle",
    "start_http_proxy", "http_port", "start_rpc_ingress",
    "rpc_ingress_port", "deploy_config",
    "DeploymentHandle", "DeploymentResponse", "StreamingResponse",
    "multiplexed", "get_multiplexed_model_id",
    "batch",
]

# Usage tagging (ref: usage_lib.record_library_usage; local-only,
# see ray_tpu/util/usage_stats.py)
from ray_tpu.util.usage_stats import record_library_usage as _rlu

_rlu("serve")
del _rlu
