"""Model multiplexing: many models time-share one replica pool.

Reference: `serve.multiplexed` + `serve.get_multiplexed_model_id`
(ref: python/ray/serve/multiplex.py, api.py multiplexed decorator).
A replica keeps an LRU cache of loaded models; the router prefers the
replica that already holds the requested model (affinity lives in the
handle's routing table — the reference keeps it in the replica scheduler,
pow_2_scheduler.py multiplexed locality).

    class MultiModel:
        @serve.multiplexed(max_num_models_per_replica=3)
        def get_model(self, model_id: str):
            return load_weights(model_id)

        def __call__(self, request):
            model = self.get_model(serve.get_multiplexed_model_id())
            return model(request)

    handle.options(multiplexed_model_id="m1").remote(...)
"""
from __future__ import annotations

import contextvars
import functools
import threading
from collections import OrderedDict
from typing import Callable, Optional

_model_id_ctx: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("raytpu_multiplexed_model_id", default=None)


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (ref: serve/api.py
    get_multiplexed_model_id)."""
    return _model_id_ctx.get() or ""


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorate a model-loader method: calls are LRU-cached per replica by
    model id; evicted models call their `__del__`/`unload` if present."""

    def deco(load_fn: Callable):
        # Per-process state is reached through the module-level accessor
        # (pickled by reference): a lock captured in this closure would
        # make the decorated class unpicklable when the deployment ships
        # to its replica actor.
        import uuid

        state_key = uuid.uuid4().hex

        @functools.wraps(load_fn)
        def wrapper(*args, **kwargs):
            st = _state_for(state_key)
            cache, lock, loading = st["cache"], st["lock"], st["loading"]
            # Supports methods (self, model_id) and functions (model_id,),
            # positionally or as model_id=... .
            model_id = kwargs.get("model_id", args[-1] if args else "")
            while True:
                with lock:
                    if model_id in cache:
                        cache.move_to_end(model_id)
                        return cache[model_id]
                    ev = loading.get(model_id)
                    if ev is None:
                        loading[model_id] = ev = threading.Event()
                        break  # this thread loads
                # Another request is loading this model: wait, re-check.
                ev.wait(timeout=600)
            try:
                model = load_fn(*args, **kwargs)
                with lock:
                    cache[model_id] = model
                    while len(cache) > max_num_models_per_replica:
                        _, evicted = cache.popitem(last=False)
                        unload = getattr(evicted, "unload", None)
                        if callable(unload):
                            try:
                                unload()
                            except Exception:  # noqa: BLE001
                                pass
                return model
            finally:
                with lock:
                    loading.pop(model_id, None)
                ev.set()

        wrapper._is_multiplexed = True
        return wrapper

    return deco


_states: dict = {}
_states_lock = threading.Lock()


def _state_for(key: str) -> dict:
    with _states_lock:
        st = _states.get(key)
        if st is None:
            st = _states[key] = {"cache": OrderedDict(),
                                 "lock": threading.Lock(),
                                 "loading": {}}
        return st
