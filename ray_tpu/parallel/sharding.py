"""Logical-axis sharding rules.

Parameters are annotated with *logical* axis names ("embed", "heads",
"mlp", "vocab", ...) and a rule table maps those to mesh axes.  Swapping the
rule table re-lays-out the whole model — DDP, FSDP, 2-D (fsdp×tp), or
3-D (fsdp×tp×sp) — with zero model-code changes.  This replaces the
reference's wrapper-class-per-strategy approach
(reference: python/ray/train/torch/train_loop_utils.py:158 `prepare_model`
DDP/FSDP branches; python/ray/train/lightning/_lightning_utils.py:83
`RayFSDPStrategy`): on TPU the strategy is a sharding annotation, not a
module wrapper.
"""
from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import AXIS_DATA, AXIS_EXPERT, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR

# rule table: logical axis name -> mesh axis (or tuple of mesh axes, or None)
LogicalRules = Mapping[str, Any]

# The workhorse layout: batch over (dp, fsdp); params sharded over fsdp on
# their largest axis and over tp on the head/mlp axis; sequence over sp.
DEFAULT_RULES: LogicalRules = {
    "batch": (AXIS_DATA, AXIS_FSDP),
    "seq": AXIS_SEQ,
    "embed": AXIS_FSDP,
    "heads": AXIS_TENSOR,
    "kv_heads": AXIS_TENSOR,
    "head_dim": None,
    "mlp": AXIS_TENSOR,
    "vocab": AXIS_TENSOR,
    "expert": AXIS_EXPERT,
    "layers": None,
}

# Inference layout: params split over tp on their head/mlp/vocab axes,
# everything else replicated — the serving analogue (decode has no
# batch axis worth sharding; a model too big for one chip splits over
# tp and XLA inserts the all-reduces after wo / w_down). Experts stay
# replicated so the rules work on a tp-only mesh.
TP_RULES: LogicalRules = {
    "batch": None, "seq": None, "embed": None,
    "heads": AXIS_TENSOR, "kv_heads": AXIS_TENSOR, "head_dim": None,
    "mlp": AXIS_TENSOR, "vocab": AXIS_TENSOR, "expert": None,
    "layers": None,
}

# Pure data-parallel: replicate every parameter (DDP-equivalent).
DDP_RULES: LogicalRules = {
    "batch": (AXIS_DATA, AXIS_FSDP),
    "seq": None, "embed": None, "heads": None, "kv_heads": None,
    "head_dim": None, "mlp": None, "vocab": None, "expert": AXIS_EXPERT,
    "layers": None,
}


def logical_to_mesh(logical: Sequence[str | None], rules: LogicalRules = DEFAULT_RULES) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    out = []
    used: set[str] = set()
    for name in logical:
        axis = rules.get(name) if name is not None else None
        # A mesh axis may appear only once in a spec; later conflicts replicate.
        if axis is None:
            out.append(None)
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    return P(*out)


def param_shardings(logical_tree: Any, mesh: Mesh, rules: LogicalRules = DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda logical: NamedSharding(mesh, logical_to_mesh(logical, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_pytree(tree: Any, shardings: Any):
    """Device_put a pytree onto its shardings (host → sharded device arrays)."""
    return jax.tree.map(jax.device_put, tree, shardings)


def with_logical_constraint(x: jax.Array, logical: Sequence[str | None],
                            rules: LogicalRules = DEFAULT_RULES) -> jax.Array:
    """`lax.with_sharding_constraint` by logical names; no-op outside a mesh ctx."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.shape:  # pragma: no cover - old jax
            return x
        spec = logical_to_mesh(logical, rules)
        # Drop mesh axes the current mesh doesn't carry.
        known = set(mesh.axis_names)
        clean = []
        for part in spec:
            if part is None:
                clean.append(None)
            elif isinstance(part, tuple):
                kept = tuple(p for p in part if p in known)
                clean.append(kept if kept else None)
            else:
                clean.append(part if part in known else None)
        return jax.lax.with_sharding_constraint(x, P(*clean))
    except Exception:
        return x
