"""TPU-native parallelism substrate.

Where the reference delegates intra-node parallelism to NCCL/torch.distributed
(ray/python/ray/util/collective/collective.py, ray/python/ray/train/torch/config.py:112),
this package expresses it the XLA way: a `jax.sharding.Mesh` over the slice,
logical-axis sharding rules on parameter pytrees, and compiler-inserted
collectives over ICI.  Host-level (out-of-graph, DCN) collectives live in
`ray_tpu.util.collective`.
"""
from ray_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    local_mesh,
    mesh_shape_for,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_TENSOR,
    AXIS_SEQ,
    AXIS_EXPERT,
)
from ray_tpu.parallel.sharding import (
    LogicalRules,
    DEFAULT_RULES,
    logical_to_mesh,
    shard_pytree,
    with_logical_constraint,
    param_shardings,
)
from ray_tpu.parallel.collectives import (
    all_gather,
    all_to_all,
    pmean,
    ppermute_ring,
    psum,
    psum_scatter,
)

__all__ = [
    "MeshConfig",
    "build_mesh",
    "local_mesh",
    "mesh_shape_for",
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_TENSOR",
    "AXIS_SEQ",
    "AXIS_EXPERT",
    "LogicalRules",
    "DEFAULT_RULES",
    "logical_to_mesh",
    "shard_pytree",
    "with_logical_constraint",
    "param_shardings",
    "psum",
    "pmean",
    "all_gather",
    "psum_scatter",
    "all_to_all",
    "ppermute_ring",
]
