"""Pipeline parallelism: GPipe microbatching over a `pp` mesh axis.

The reference has no first-class pipeline parallelism (SURVEY §2.4 — "PP:
No first-class impl"); its compiled-DAG channels exist to wire actor-stage
pipelines by hand (ref: python/ray/dag/compiled_dag_node.py:174). On TPU
the idiomatic build is SPMD: stages are a mesh axis, layer params are
sharded over it, and activations move stage→stage with `lax.ppermute`
over ICI neighbors inside one compiled program — no runtime scheduler, no
host round-trips, and the bubble is the only overhead.

Schedule: GPipe. With S stages and M microbatches the loop runs
M + S - 1 ticks; each tick every stage applies its layer block to the
activation it holds, then rotates activations one hop along the ring.
Stage 0 feeds fresh microbatches in; the last stage collects outputs.
Backward flows through the same program via autodiff (`ppermute`'s
transpose is the inverse permutation), so the 1F1B-style memory savings
come from `jax.checkpoint` around the stage body rather than a manual
schedule.

Composes with data parallelism: the mesh is (dp, pp); the batch is
sharded over dp and microbatched over pp time.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.transformer import (
    TransformerConfig, _block, init_params)
from ray_tpu.ops.norms import rms_norm

AXIS_PIPE = "pp"


def build_pipeline_mesh(n_stages: int, dp: int = 1,
                        devices=None) -> Mesh:
    """A (dp, pp) mesh. pp is innermost so stage hops ride ICI neighbors."""
    devices = list(devices if devices is not None else jax.devices())
    need = n_stages * dp
    if len(devices) < need:
        raise ValueError(f"need {need} devices for dp={dp} x pp={n_stages}, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(dp, n_stages)
    return Mesh(arr, axis_names=("dp", AXIS_PIPE))


def _stage_params_spec(cfg: TransformerConfig):
    """PartitionSpecs: block stack sharded over pp on the layer axis,
    embedding/head replicated (stage 0 / last stage use them)."""
    specs = {
        "embed": P(),
        "blocks": jax.tree.map(lambda _: P(AXIS_PIPE), _blocks_template(cfg)),
        "final_norm": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P()
    return specs


def _blocks_template(cfg: TransformerConfig):
    keys = ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
            "w_gate", "w_up", "w_down"]
    if cfg.n_experts > 0:
        keys.append("router")
    return {k: 0 for k in keys}


def make_pipeline_loss(cfg: TransformerConfig, mesh: Mesh,
                       n_microbatches: int) -> Callable:
    """loss(params, batch) -> scalar, pipelined over mesh's pp axis.

    Numerically equivalent to `models.transformer.loss_fn` (tested on the
    virtual CPU mesh): same blocks, same cross entropy, microbatched on
    the batch dimension.
    """
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "pipeline + MoE: route experts inside a stage via the ep axis")
    n_stages = mesh.shape[AXIS_PIPE]
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"pp={n_stages}")
    M = n_microbatches
    cd = cfg.compute_dtype

    # Reference attention inside the stage body: the pallas kernel path is
    # picked per-shape by flash_attention; inside shard_map we call the
    # dispatcher directly on the local (microbatch) view.
    from ray_tpu.ops.attention import flash_attention

    def run_stage(x, blocks, positions):
        body = functools.partial(
            _block, cfg=cfg, rules={},
            attn_impl=lambda q, k, v: flash_attention(q, k, v, True, None),
            positions=positions)
        if cfg.remat:
            body = jax.checkpoint(body)

        def scan_body(h, bp):
            h, _ = body(h, bp)
            return h, None

        x, _ = jax.lax.scan(scan_body, x, blocks)
        return x

    def pipelined(params, tokens, targets, mask):
        # Local views: tokens (Bl, T), blocks leading dim L/S.
        S = n_stages
        stage = jax.lax.axis_index(AXIS_PIPE)
        bl, t = tokens.shape
        if bl % M:
            raise ValueError(f"local batch {bl} not divisible by "
                             f"n_microbatches={M}")
        mb = bl // M
        positions = jnp.arange(t, dtype=jnp.int32)

        x_all = params["embed"].astype(cd)[tokens]          # (Bl, T, d)
        x_all = x_all.reshape(M, mb, t, cfg.d_model)

        outs0 = jnp.zeros((M, mb, t, cfg.d_model), cd)
        act0 = jnp.zeros((mb, t, cfg.d_model), cd)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, i):
            act, outs = carry
            x_in = jnp.where(stage == 0, x_all[jnp.clip(i, 0, M - 1)], act)
            y = run_stage(x_in, params["blocks"], positions)
            idx = i - (S - 1)
            valid = jnp.logical_and(idx >= 0, idx < M)
            is_last = stage == S - 1
            slot = jnp.clip(idx, 0, M - 1)
            upd = jnp.where(jnp.logical_and(valid, is_last), y, outs[slot])
            outs = outs.at[slot].set(upd)
            y_next = jax.lax.ppermute(y, AXIS_PIPE, perm)
            return (y_next, outs), None

        (_, outs), _ = jax.lax.scan(tick, (act0, outs0),
                                    jnp.arange(M + S - 1))

        # Loss on the last stage only; psum makes it uniform across pp.
        h = outs.reshape(bl, t, cfg.d_model)
        h = rms_norm(h, params["final_norm"], eps=cfg.norm_eps)
        if cfg.tie_embeddings:
            logits = jnp.einsum("btd,vd->btv", h, params["embed"].astype(cd))
        else:
            logits = jnp.einsum("btd,dv->btv", h, params["lm_head"].astype(cd))
        logits = logits.astype(jnp.float32)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        nll = (logz - tgt) * mask
        local_sum = jnp.sum(jnp.where(stage == S - 1, nll, 0.0))
        total_sum = jax.lax.psum(local_sum, AXIS_PIPE)
        total_sum = jax.lax.psum(total_sum, "dp")
        # Token count from the mask (psum over dp; pp holds replicas).
        n_tokens = jax.lax.psum(jnp.sum(mask), "dp")
        return total_sum / jnp.maximum(n_tokens, 1.0)

    pspec = _stage_params_spec(cfg)
    sharded = shard_map(
        pipelined, mesh=mesh,
        in_specs=(pspec, P("dp"), P("dp"), P("dp")),
        out_specs=P(),
        check_vma=False)

    def loss(params, batch):
        tokens = batch["tokens"]
        if "targets" in batch:
            inputs, targets = tokens, batch["targets"]
        else:
            inputs, targets = tokens[:, :-1], tokens[:, 1:]
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(targets.shape, jnp.float32)
        return sharded(params, inputs, targets, mask.astype(jnp.float32))

    return loss


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PipelineTrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def make_pipeline_train_step(
    cfg: TransformerConfig, mesh: Mesh, *,
    n_microbatches: int,
    optimizer: optax.GradientTransformation | None = None,
) -> tuple[Callable, Callable]:
    """(init_fn, step_fn) with layer params sharded over the pp axis.

    Gradients for stage-sharded params stay local to their stage; grads of
    the replicated embedding/head are psum'd by shard_map's transpose —
    XLA lays both on ICI.
    """
    optimizer = optimizer or optax.adamw(1e-3)
    loss = make_pipeline_loss(cfg, mesh, n_microbatches)
    pspec = _stage_params_spec(cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), pspec,
        is_leaf=lambda x: isinstance(x, P))

    def init_fn(rng) -> PipelineTrainState:
        params = jax.jit(
            lambda r: init_params(r, cfg), out_shardings=shardings)(rng)
        opt_state = optimizer.init(params)
        return PipelineTrainState(jnp.zeros((), jnp.int32), params, opt_state)

    @jax.jit
    def step_fn(state: PipelineTrainState, batch):
        lval, grads = jax.value_and_grad(loss)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return PipelineTrainState(state.step + 1, params, opt_state), {
            "loss": lval}

    return init_fn, step_fn


def dryrun_pipeline(n_devices: int) -> None:
    """Tiny 2-stage GPipe step on the virtual mesh (driver dry-run hook)."""
    from ray_tpu.models import configs

    pp = 2
    dp = max(1, min(2, n_devices // pp))
    mesh = build_pipeline_mesh(pp, dp=dp)
    cfg = dataclasses.replace(configs.TINY, n_layers=2, d_model=64,
                              d_ff=128, n_heads=4, n_kv_heads=4, remat=False)
    init_fn, step_fn = make_pipeline_train_step(
        cfg, mesh, n_microbatches=2, optimizer=optax.sgd(1e-3))
    state = init_fn(jax.random.key(0))
    tokens = jnp.zeros((4 * dp, 33), jnp.int32)
    state, metrics = step_fn(state, {"tokens": tokens})
    float(metrics["loss"])
    assert int(state.step) == 1
