"""In-graph collective wrappers.

The reference exposes host-level collectives over NCCL/GLOO
(reference: python/ray/util/collective/collective.py:258 `allreduce`,
:472 `reducescatter`, :531/:594 `send/recv`).  Inside an SPMD program the
TPU-native equivalents are `jax.lax` collectives compiled onto ICI; these
wrappers only add axis-name ergonomics and a ring-permute helper used by
ring attention.  Host-level (out-of-graph) collectives are in
`ray_tpu.util.collective`.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis: str | Sequence[str]):
    return lax.psum(x, axis)


def pmean(x, axis: str | Sequence[str]):
    return lax.pmean(x, axis)


def all_gather(x, axis: str, *, dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=dim, tiled=tiled)


def psum_scatter(x, axis: str, *, dim: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=tiled)


def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int, tiled: bool = True):
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim,
                          tiled=tiled)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)


def ppermute_ring(x, axis: str, *, shift: int = 1):
    """Rotate shards around the `axis` ring by `shift` (neighbor exchange on ICI).

    perm[i] = (i + shift) % n: device i's value lands on device i+shift, i.e.
    each device receives the value of its `-shift` neighbor.
    """
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def unshard(x):
    """Gather a sharded global array to a host numpy array (debug/eval path)."""
    import numpy as np
    return np.asarray(jax.device_get(x))
