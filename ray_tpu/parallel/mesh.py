"""Device-mesh construction for TPU slices.

The reference has no mesh concept — its parallelism topology is implicit in
NCCL process-group ranks (reference: python/ray/train/torch/config.py:112
`_setup_torch_process_group`).  On TPU the topology is explicit and physical:
chips are wired in an ICI torus, and XLA lays collectives onto it.  We name
five standard axes and build meshes with `mesh_utils.create_device_mesh` so
that axis order maps contiguous ICI neighborhoods to the inner axes
(tensor/seq), keeping the bandwidth-hungry collectives on ICI rather than DCN.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis names, outermost (DCN-friendly) to innermost (ICI-hungry).
AXIS_DATA = "dp"      # pure data parallel: gradient psum only
AXIS_FSDP = "fsdp"    # data parallel with parameter sharding (ZeRO-3 / XLA SPMD)
AXIS_EXPERT = "ep"    # MoE expert parallel: all_to_all token routing
AXIS_SEQ = "sp"       # sequence/context parallel: ring attention ppermute
AXIS_TENSOR = "tp"    # tensor (Megatron) parallel: activation all-reduce

_CANONICAL_ORDER = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis.  -1 on at most one axis means
    "absorb all remaining devices" (like torch's device_mesh -1)."""

    dp: int = 1
    fsdp: int = -1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {AXIS_DATA: self.dp, AXIS_FSDP: self.fsdp,
                 AXIS_EXPERT: self.ep, AXIS_SEQ: self.sp, AXIS_TENSOR: self.tp}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} wants {fixed} devices but {n_devices} are available")
        return sizes


def mesh_shape_for(n_devices: int, config: MeshConfig | None = None) -> dict[str, int]:
    return (config or MeshConfig()).resolve(n_devices)


def build_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis_order: Sequence[str] = _CANONICAL_ORDER,
) -> Mesh:
    """Build a Mesh whose trailing axes sit on contiguous ICI neighborhoods.

    `mesh_utils.create_device_mesh` understands the physical TPU topology and
    permutes devices so that the innermost mesh axes are nearest-neighbor on
    the ICI torus — exactly where tp/sp collectives must live.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = mesh_shape_for(len(devices), config)
    shape = tuple(sizes[a] for a in axis_order)
    if devices[0].platform == "tpu":
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        # CPU/GPU test path: topology is flat, plain reshape is fine.
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axis_order))


def local_mesh(n: int | None = None) -> Mesh:
    """A 1-D fsdp mesh over (the first n) local devices; the everyday default."""
    devices = jax.devices()[: n or len(jax.devices())]
    return build_mesh(MeshConfig(fsdp=-1), devices=devices)


def mesh_axis_sizes(mesh: Mesh) -> Mapping[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
