"""HuggingFace Transformers integration for ray_tpu.train.

Analogue of the reference glue (ref: python/ray/train/huggingface/
transformers/_transformers_utils.py — RayTrainReportCallback :30 bridges
transformers' logging into train.report; prepare_trainer :104 wires the
distributed context into the HF Trainer). Used inside a
TorchTrainer/JaxTrainer train loop:

    def train_loop(config):
        trainer = transformers.Trainer(...)
        trainer.add_callback(RayTrainReportCallback())
        trainer = prepare_trainer(trainer)
        trainer.train()
"""
from __future__ import annotations

import os
import tempfile
from typing import Any, Optional

try:
    from transformers.trainer_callback import TrainerCallback
except ImportError:  # pragma: no cover — transformers not installed
    TrainerCallback = object


class RayTrainReportCallback(TrainerCallback):
    """Report HF Trainer logs (and checkpoints when HF saves one) to the
    ray_tpu.train session (ref: _transformers_utils.py:30)."""

    def on_log(self, args, state, control, logs=None, **kwargs):
        if not logs:
            return
        from ray_tpu.train.session import report

        metrics = {k: v for k, v in logs.items()
                   if isinstance(v, (int, float))}
        metrics.setdefault("step", state.global_step)
        metrics.setdefault("epoch", float(state.epoch or 0))
        try:
            report(metrics)
        except RuntimeError:
            pass  # not inside a train session (plain HF run): no-op

    def on_save(self, args, state, control, **kwargs):
        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.train.session import report

        ckpt_dir = os.path.join(
            args.output_dir, f"checkpoint-{state.global_step}")
        if not os.path.isdir(ckpt_dir):
            return
        try:
            report({"step": state.global_step,
                    "checkpoint_saved": 1.0},
                   checkpoint=Checkpoint(ckpt_dir))
        except RuntimeError:
            pass


def prepare_trainer(trainer: Any) -> Any:
    """Wire the distributed session context into an HF Trainer (ref:
    _transformers_utils.py:104): world size/rank come from the gang, and
    non-rank-0 workers silence their progress bars."""
    from ray_tpu.train.session import get_context

    ctx = get_context()
    try:
        rank = ctx.get_world_rank()
        world = ctx.get_world_size()
    except RuntimeError:
        return trainer  # not inside a train session
    if rank != 0:
        # Progress/report callbacks are resolved inside Trainer.__init__
        # — mutating trainer.args after the fact does nothing; the
        # callbacks themselves must go (one progress bar / one wandb run
        # per gang, not per worker).
        try:
            from transformers.trainer_callback import (
                PrinterCallback,
                ProgressCallback,
            )

            trainer.remove_callback(ProgressCallback)
            trainer.remove_callback(PrinterCallback)
            from transformers.integrations import (
                get_reporting_integration_callbacks,
            )

            for cb_cls in get_reporting_integration_callbacks(
                    trainer.args.report_to):
                trainer.remove_callback(cb_cls)
        except Exception:  # noqa: BLE001 transformers-version drift
            pass
        trainer.args.disable_tqdm = True
        if world > 1:
            # Per-worker output dirs under the TRIAL directory: stable
            # across fault-tolerant restarts (resume_from_checkpoint
            # finds prior checkpoints) and unique per trial. Concurrent
            # runs must use distinct RunConfig names — the trial dir
            # (checkpoints included) is shared per name, the same
            # contract the reference's storage layout has.
            try:
                base = ctx.get_trial_dir()
            except RuntimeError:
                base = tempfile.mkdtemp(prefix="hf_gang_")
            trainer.args.output_dir = os.path.join(
                base, f"hf_worker_{rank}")
            os.makedirs(trainer.args.output_dir, exist_ok=True)
    return trainer


def prepare_model(model: Any, device: Optional[str] = None) -> Any:
    """Torch-model preparation inside a gang (ref: train/torch/
    train_loop_utils.py:158 prepare_model — DDP/FSDP wrap). Under the
    torch-gloo backend the process group is already initialized by the
    JaxTrainer/TorchTrainer backend; this wraps in DDP when distributed
    is live, else returns the model unchanged."""
    import torch

    if device is not None:
        model = model.to(device)
    if torch.distributed.is_available() \
            and torch.distributed.is_initialized() \
            and torch.distributed.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model
