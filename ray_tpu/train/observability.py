"""Train-plane goodput observability: per-step phase attribution.

The worker half of the train observability stack. A training run is a
black box between `train.report()` calls unless the step loop is
instrumented, and TPU training efficiency is dominated by exactly the
stalls a wall clock can't see: input-pipeline waits and slowest-rank
synchronization barriers. This module attributes every second of a
rank's step loop to one of five buckets:

  data_wait    blocked on the input pipeline (auto-charged by
               `data/streaming/prefetch.py` when the device prefetcher
               blocks, and by the iterator wrapper
               `session.get_dataset_shard` installs — StreamingIngest
               loops get it for free)
  compute      time the user marks with `train.phase("compute")`
  sync         cross-rank barriers the user marks (allreduce, pjit
               dispatch fences)
  checkpoint   `train.report(checkpoint=...)`'s persist — timed
               automatically by the session
  other        the unattributed remainder of each step; counted as
               productive by the GCS goodput split (a stall you did
               not measure cannot be blamed)

Steps are delimited either explicitly (`with train.step_phases():`)
or implicitly — each `train.report()` closes the open step — so
uninstrumented loops still produce step timing, skew windows, and
goodput splits.

Everything federates over existing planes, no new RPCs: cumulative
counters ride the gauge → node daemon → syncer → GCS path (the serve
replica gauge precedent), histograms ride the piggybacked registry
dump, and per-step spans (trace_id == run id == experiment name +
fit attempt) ride the worker TaskEventBuffer span flush into the GCS
TaskEvents sink, where `ray-tpu train trace <run>` finds them.

Kill switch: RAY_TPU_TRAIN_OBS_ENABLED=0 turns all of it off — the
recorder becomes a no-op shell, no pusher thread starts, no spans
mint.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.core.config import get_config
from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Histogram, registry_dump

PHASES = ("data_wait", "compute", "sync", "checkpoint")

# Step/phase wall times live in the 1ms..minutes band (a TPU step is
# rarely sub-millisecond); the default RPC-latency boundaries waste
# their sub-ms floor here.
_STEP_BOUNDARIES = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1, 2.5, 5, 10, 30, 60, 300)

_M: Optional[dict] = None
_m_lock = threading.Lock()


def _metrics() -> dict:
    global _M
    with _m_lock:
        if _M is None:
            _M = {
                "step_seconds": Histogram(
                    "raytpu_train_step_seconds",
                    "Wall time of one training step on one rank",
                    _STEP_BOUNDARIES, ("run", "rank")),
                "phase_seconds": Histogram(
                    "raytpu_train_phase_seconds",
                    "Per-step time attributed to one phase on one rank",
                    _STEP_BOUNDARIES, ("run", "rank", "phase")),
                "persist_seconds": Histogram(
                    "raytpu_train_checkpoint_persist_seconds",
                    "Wall time of train.report()'s checkpoint persist "
                    "(rmtree + copytree into the trial dir)",
                    _STEP_BOUNDARIES, ("run", "rank")),
                "steps_total": Counter(
                    "raytpu_train_steps_total",
                    "Training steps completed", ("run", "rank")),
            }
        return _M


# The process-wide active recorder. One training session exists per
# worker process (train/session.py module global); the device
# prefetcher and benches reach the recorder through this hook without
# importing the session machinery.
_active: Optional["StepPhaseRecorder"] = None


def get_active() -> Optional["StepPhaseRecorder"]:
    return _active


def set_active(rec: Optional["StepPhaseRecorder"]) -> None:
    global _active
    _active = rec


def on_data_wait(seconds: float) -> None:
    """Charge input-pipeline block time to the active recorder's
    current step. Best-effort hook for `data/streaming/prefetch.py`:
    no active session (plain Dataset consumption outside a train
    loop) means no-op."""
    rec = _active
    if rec is not None:
        rec.add_phase("data_wait", seconds)


_run_seq_lock = threading.Lock()
_run_seq: Dict[str, int] = {}


def next_run_id(experiment: str) -> str:
    """Mint the run id for one fit(): experiment name + fit attempt
    ("mnist#0", "mnist#1", ...). Stable across gang restarts WITHIN a
    fit — satellite requirement: the failover leg of a chaos run shows
    up in the same trace — while separate fits of the same experiment
    get distinct traces."""
    with _run_seq_lock:
        seq = _run_seq.get(experiment, 0)
        _run_seq[experiment] = seq + 1
    return f"{experiment}#{seq}"


def emit_run_event(run: str, run_id: str, message: str,
                   severity: str = "INFO", **fields) -> None:
    """Best-effort train-plane event into the GCS EventLog
    (source="train"): gang starts carry the restart gap the
    TrainRunState charges to `lost_restart`, joining the elastic
    supervisor's own restart/shrink/grow events."""
    if not get_config().train_obs_enabled:
        return
    try:
        from ray_tpu.api import _global_worker

        _global_worker().gcs.call(
            "EventLog", "add_event", source="train", severity=severity,
            message=message,
            fields={"run": run, "run_id": run_id, **fields}, timeout=10)
    except Exception:  # noqa: BLE001 — events are best-effort
        pass


class _PhaseTimer:
    __slots__ = ("_rec", "_name", "_t0")

    def __init__(self, rec: "StepPhaseRecorder", name: str):
        self._rec = rec
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.add_phase(self._name, time.perf_counter() - self._t0)
        return False


class _NullTimer:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


class StepPhaseRecorder:
    """Per-rank step/phase accounting for one run attempt.

    Owned by the TrainSession (or constructed standalone by benches).
    Thread-safe for the one-writer-per-phase pattern the train loop
    uses: the user thread opens/closes steps and phases while the
    pusher thread reads cumulative totals under the same lock.
    """

    def __init__(self, run: str, run_id: str, rank: int, world_size: int,
                 attempt: int = 0,
                 flops_per_step: Optional[float] = None,
                 enabled: Optional[bool] = None):
        cfg = get_config()
        self.enabled = (cfg.train_obs_enabled if enabled is None
                        else bool(enabled))
        self.run = run                  # experiment name (gauge key)
        self.run_id = run_id            # trace id: "<name>#<fit-seq>"
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.attempt = int(attempt)     # gang-restart index within a fit
        self.flops_per_step = flops_per_step
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=max(1,
                                               cfg.train_obs_window_steps))
        self._trace_steps = cfg.train_obs_trace_steps
        self.started_ts = time.time()
        self.steps_total = 0
        self.first_step: Optional[int] = None
        self.last_step: Optional[int] = None
        self.last_step_ts: float = 0.0
        self.step_s_total = 0.0
        self.phase_s: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.phase_s["other"] = 0.0
        # Open-step state (None between steps).
        self._step_t0: Optional[float] = None
        self._step_wall0: Optional[float] = None
        self._step_phases: Dict[str, float] = {}
        self._step_intervals: List[tuple] = []
        self._step_explicit = False
        self._tags = {"run": self.run, "rank": str(self.rank)}

    # -- step lifecycle ---------------------------------------------------

    def step_start(self, explicit: bool = False) -> None:
        if not self.enabled:
            return
        with self._lock:
            if self._step_t0 is None:
                self._step_t0 = time.perf_counter()
                self._step_wall0 = time.time()
                self._step_phases = {}
                self._step_intervals = []
            self._step_explicit = self._step_explicit or explicit

    def step_end(self) -> None:
        """Close the open step: fold measured phases, charge the
        unattributed remainder to `other`, observe histograms, mint
        spans. No-op when no step is open."""
        if not self.enabled:
            return
        with self._lock:
            if self._step_t0 is None:
                return
            wall = time.perf_counter() - self._step_t0
            wall0 = self._step_wall0 or time.time()
            phases = self._step_phases
            intervals = self._step_intervals
            self._step_t0 = None
            self._step_wall0 = None
            self._step_phases = {}
            self._step_intervals = []
            self._step_explicit = False
            step_index = self.steps_total
            self.steps_total += 1
            if self.first_step is None:
                self.first_step = step_index
            self.last_step = step_index
            self.last_step_ts = time.time()
            self.step_s_total += wall
            other = wall
            for name, dur in phases.items():
                self.phase_s[name] = self.phase_s.get(name, 0.0) + dur
                other -= dur
            other = max(0.0, other)
            self.phase_s["other"] += other
            self._window.append(wall)
        m = _metrics()
        m["step_seconds"].observe(wall, self._tags)
        m["steps_total"].inc(1, self._tags)
        for name, dur in phases.items():
            m["phase_seconds"].observe(dur, {**self._tags, "phase": name})
        self._mint_step_span(step_index, wall0, wall0 + wall, phases,
                             intervals, other)

    def _mint_step_span(self, step_index, start_ts, end_ts, phases,
                        intervals, other) -> None:
        if self._trace_steps == 0 or step_index >= self._trace_steps:
            return
        parent = tracing.record_train_span(
            self.run_id, "train.step", start_ts, end_ts,
            rank=self.rank, step=step_index, attempt=self.attempt,
            other_s=round(other, 6),
            **{f"{k}_s": round(v, 6) for k, v in phases.items()})
        if parent is None:
            return
        for name, t0, t1 in intervals:
            tracing.record_train_span(
                self.run_id, f"phase.{name}", t0, t1, parent_id=parent,
                rank=self.rank, step=step_index, attempt=self.attempt)

    # -- phases -----------------------------------------------------------

    def phase(self, name: str):
        """Context manager attributing the block's wall time to `name`
        within the current step (opening one implicitly if needed).
        Unknown names are allowed — they show up as their own
        attribution bucket but are not part of the goodput split."""
        if not self.enabled:
            return _NULL_TIMER
        self.step_start()
        return _PhaseTimer(self, name)

    def add_phase(self, name: str, seconds: float) -> None:
        """Charge already-measured time to a phase of the current step
        (the after-the-fact entry point: prefetcher block times, the
        report() persist)."""
        if not self.enabled or seconds <= 0:
            return
        with self._lock:
            if self._step_t0 is None:
                # Time measured outside any step (e.g. the warmup fetch
                # before the loop): open an implicit step backdated to
                # when the measured block began, so the step's wall
                # covers the time just charged to it.
                self._step_t0 = time.perf_counter() - seconds
                self._step_wall0 = time.time() - seconds
                self._step_phases = {}
                self._step_intervals = []
            self._step_phases[name] = (self._step_phases.get(name, 0.0)
                                       + seconds)
            now = time.time()
            self._step_intervals.append((name, now - seconds, now))

    def on_report(self) -> None:
        """`train.report()` delimits implicit steps; explicit
        `step_phases()` blocks close at CM exit instead so a loop that
        reports mid-step is not cut short."""
        if not self.enabled:
            return
        with self._lock:
            explicit = self._step_explicit
        if not explicit:
            self.step_end()

    def observe_persist(self, seconds: float) -> None:
        """Satellite: the checkpoint persist used to block the user
        loop untimed — fold it into the `checkpoint` phase and export
        its own histogram so slow persists stop masquerading as slow
        steps."""
        if not self.enabled:
            return
        self.add_phase("checkpoint", seconds)
        _metrics()["persist_seconds"].observe(seconds, self._tags)

    # -- federation -------------------------------------------------------

    def gauges(self) -> Dict[str, Any]:
        """Cumulative per-rank counters for the node-daemon push. The
        GCS TrainRunState retains these across TTL expiry, so a rank
        that stops pushing (SIGSTOP, death) stays attributable."""
        with self._lock:
            window = list(self._window)
            out: Dict[str, Any] = {
                "rank": self.rank,
                "world": self.world_size,
                "attempt": self.attempt,
                "run_id": self.run_id,
                "started_ts": self.started_ts,
                "steps": self.steps_total,
                "first_step": self.first_step,
                "last_step": self.last_step,
                "last_step_ts": self.last_step_ts,
                "step_s": round(self.step_s_total, 6),
            }
            for name, total in self.phase_s.items():
                out[f"{name}_s"] = round(total, 6)
        if window:
            out["window_steps"] = len(window)
            out["window_step_s"] = round(sum(window), 6)
        if self.flops_per_step:
            out["flops_per_step"] = float(self.flops_per_step)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Local attribution summary (benches, tests): cumulative phase
        seconds plus the derived busy fraction — productive share of
        attributed wall time, where unmeasured remainder counts as
        productive (same optimistic split the GCS applies)."""
        g = self.gauges()
        total = g.get("step_s", 0.0)
        busy = g.get("compute_s", 0.0) + g.get("other_s", 0.0)
        g["busy_fraction"] = (busy / total) if total > 0 else 0.0
        return g


@contextlib.contextmanager
def step(rec: Optional["StepPhaseRecorder"]):
    """One explicit training step on `rec` (None-safe): phases inside
    attribute to this step; the step closes at block exit."""
    if rec is None or not rec.enabled:
        yield rec
        return
    rec.step_start(explicit=True)
    try:
        yield rec
    finally:
        rec.step_end()


class PhasedIterator:
    """Iterator wrapper charging `__next__` block time to `data_wait`
    — what `session.get_dataset_shard` installs around plain-iterable
    shards so hand-fed loops get input attribution for free (Dataset
    shards get it from the device prefetcher hook instead)."""

    def __init__(self, it, rec: Optional["StepPhaseRecorder"] = None):
        self._it = iter(it)
        self._rec = rec

    def __iter__(self):
        return self

    def __next__(self):
        rec = self._rec if self._rec is not None else _active
        if rec is None or not rec.enabled:
            return next(self._it)
        t0 = time.perf_counter()
        try:
            return next(self._it)
        finally:
            rec.add_phase("data_wait", time.perf_counter() - t0)


class GaugePusher:
    """Background per-rank gauge push to the local node daemon
    (modeled on serve/replica.py's `_gauge_loop`): cumulative step and
    phase counters every `train_obs_push_s`, with the process metric
    registry piggybacked so the per-rank histograms reach the GCS
    federation. Local mode (no daemon) degrades to registry-only."""

    def __init__(self, rec: StepPhaseRecorder):
        self._rec = rec
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if not self._rec.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="train-obs-push", daemon=True)
        self._thread.start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if flush:
            self._push_once()
            self._flush_spans()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    @staticmethod
    def _flush_spans() -> None:
        """Synchronously ship any step/phase spans still buffered in
        this process. The gang's workers are torn down right after the
        loop fn returns, before the event flusher's next tick — without
        this a short failover leg (restart, finish in under one flush
        period) leaves its whole trace in a dying process."""
        try:
            from ray_tpu.api import _global_worker, is_initialized

            if not is_initialized():
                return
            core = _global_worker()
            buf = getattr(core, "task_events", None)
            loop = getattr(core, "loop_thread", None)
            if buf is None or loop is None:
                return
            loop.run(buf.flush_final(), timeout=5)
        except Exception:  # noqa: BLE001 telemetry must not kill training
            pass

    def _loop(self) -> None:
        period = max(0.1, get_config().train_obs_push_s)
        while not self._stop.wait(period):
            self._push_once()

    def _push_once(self) -> None:
        try:
            from ray_tpu.api import _global_worker, is_initialized

            if not is_initialized():
                return
            daemon = getattr(_global_worker(), "daemon", None)
            if daemon is None:
                return
            daemon.call("NodeDaemon", "report_train_gauges",
                        run=self._rec.run, rank=self._rec.rank,
                        gauges=self._rec.gauges(),
                        metrics=registry_dump(), timeout=2)
        except Exception:  # noqa: BLE001 telemetry must not kill training
            pass
