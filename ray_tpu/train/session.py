"""Per-worker training session: report / get_checkpoint / context.

The reference runs the user loop in a thread and funnels `train.report`
through a result queue consumed by the trainer
(ref: python/ray/train/_internal/session.py:109 `_TrainSession`, report
:661, get_checkpoint :748, get_dataset_shard :1054).  Same shape here: the
session is a module-global installed by the TrainWorker actor; `report`
enqueues (metrics, checkpoint-dir) and the trainer drains the queue via
actor polling.

The session also owns this rank's step/phase attribution
(train/observability.py): `report()` delimits implicit steps and times
the checkpoint persist into the `checkpoint` phase, `step_phases()` /
`phase()` expose explicit step markup, `get_dataset_shard` wraps plain
iterators so their blocking `next()` charges `data_wait`, and a
background pusher federates the per-rank counters over the node-daemon
gauge path.
"""
from __future__ import annotations

import os
import queue
import shutil
import threading
import time
from typing import Any, Dict, Iterable, Optional

from ray_tpu.train import observability as train_obs
from ray_tpu.train.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["TrainSession"] = None


class TrainSession:
    def __init__(self, *, world_rank: int, world_size: int, local_rank: int,
                 trial_dir: str, latest_checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 experiment_name: str = "train",
                 run_meta: Optional[Dict[str, Any]] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.trial_dir = trial_dir
        self.experiment_name = experiment_name
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.results: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        # Last moment this rank made observable progress (a report()).
        # The worker's progress probe ships this as a running-task
        # start_ts, so the daemon's hung-task watchdog flags a loop
        # that STOPPED reporting — not one that is merely long-running.
        self.last_progress_ts = time.time()
        # Seed past any checkpoints a previous (failed) attempt persisted:
        # restarting from 0 would re-target checkpoint_000001... and mix
        # stale files into — or clobber — the dir we may be restoring from.
        self._ckpt_seq = self._existing_ckpt_max()
        # Step/phase attribution for this rank (run id == experiment
        # name + fit attempt, stable across gang restarts; the restart
        # index rides along as `attempt`).
        meta = run_meta or {}
        self.run_id = meta.get("run_id") or f"{experiment_name}#0"
        self.recorder = train_obs.StepPhaseRecorder(
            run=experiment_name, run_id=self.run_id,
            rank=world_rank, world_size=world_size,
            attempt=int(meta.get("attempt", 0) or 0),
            flops_per_step=meta.get("flops_per_step"))
        self._pusher = train_obs.GaugePusher(self.recorder)

    def _existing_ckpt_max(self) -> int:
        try:
            names = os.listdir(self.trial_dir)
        except OSError:
            return 0
        best = 0
        for name in names:
            if not name.startswith("checkpoint_"):
                continue
            try:
                best = max(best, int(name.rsplit("_", 1)[1]))
            except ValueError:
                continue  # stray entry (tmp dirs etc.) — skip, don't reset
        return best

    # -- user-facing ----------------------------------------------------
    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        persisted = None
        if checkpoint is not None:
            t0 = time.perf_counter()
            self._ckpt_seq += 1
            dest = os.path.join(self.trial_dir,
                                f"checkpoint_{self._ckpt_seq:06d}")
            if os.path.abspath(checkpoint.path) != dest:
                # Fresh dir: copytree(dirs_exist_ok=True) would only
                # overwrite same-named files, leaving stale orbax leftovers.
                shutil.rmtree(dest, ignore_errors=True)
                shutil.copytree(checkpoint.path, dest)
            persisted = dest
            self.latest_checkpoint = Checkpoint(persisted)
            self.recorder.observe_persist(time.perf_counter() - t0)
        self.last_progress_ts = time.time()
        self.recorder.on_report()
        self.results.put({"metrics": dict(metrics), "checkpoint": persisted})

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        if (self.recorder.enabled
                and not hasattr(shard, "iter_batches")
                and (hasattr(shard, "__next__")
                     or hasattr(shard, "__iter__"))):
            # Plain iterator/iterable shard: time its next() into
            # data_wait. Dataset-shaped shards keep their API surface —
            # their feed goes through the device prefetcher, which
            # charges data_wait via the observability hook.
            return train_obs.PhasedIterator(shard, self.recorder)
        return shard


def install_session(s: TrainSession) -> None:
    global _session
    with _session_lock:
        _session = s
    train_obs.set_active(s.recorder)
    s._pusher.start()


def uninstall_session() -> None:
    global _session
    with _session_lock:
        prev, _session = _session, None
    if prev is not None:
        # Close any step left open, then flush a final gauge push so
        # the GCS sees the rank's terminal counters.
        prev.recorder.step_end()
        prev._pusher.stop(flush=True)
    train_obs.set_active(None)


def _get() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active — call inside train_loop_per_worker")
    return _session


# ---- public API (ray.train.* equivalents) -----------------------------
def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    _get().report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _get().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return _get().get_dataset_shard(name)


def step_phases():
    """Explicit step delimiter: `with train.step_phases() as step:` —
    phases recorded inside (via `step.phase(...)` or the module-level
    `train.phase(...)`) attribute to this step, and the step closes at
    block exit rather than at the next `report()`."""
    return train_obs.step(_get().recorder)


def phase(name: str):
    """Attribute the block's wall time to one phase
    ("data_wait"/"compute"/"sync"/"checkpoint") of the current step:
    `with train.phase("compute"): loss = train_step(...)`."""
    return _get().recorder.phase(name)


class TrainContext:
    def get_world_size(self) -> int:
        return _get().world_size

    def get_world_rank(self) -> int:
        return _get().world_rank

    def get_local_rank(self) -> int:
        return _get().local_rank

    def get_trial_dir(self) -> str:
        return _get().trial_dir

    def get_experiment_name(self) -> str:
        return _get().experiment_name

    def get_run_id(self) -> str:
        return _get().run_id


def get_context() -> TrainContext:
    return TrainContext()
