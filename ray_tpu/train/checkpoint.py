"""Checkpoints: directory handles + JAX pytree (de)serialization.

Mirrors the reference's `Checkpoint` directory-handle design
(ref: python/ray/train/_checkpoint.py:56 — a path + filesystem, moved
around by upload/download) with the TPU-native payload being an Orbax
checkpoint of a sharded pytree: every host writes its own param shards
(async), so multi-host checkpointing scales with slice size.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Iterator, Optional


class Checkpoint:
    """Handle to a checkpoint directory (local or fsspec-style path)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        """Materialize into `dest` (copy); returns the directory path."""
        if dest is None:
            dest = os.path.join(tempfile.gettempdir(),
                                f"rtpu_ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def update_metadata(self, metadata: dict) -> None:
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(metadata, f)

    def get_metadata(self) -> dict:
        p = os.path.join(self.path, ".metadata.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


def save_pytree(tree: Any, path: str, *, step: int = 0) -> Checkpoint:
    """Write a (possibly sharded) pytree with Orbax; blocks until durable."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree)
    ckptr.wait_until_finished()
    ckpt = Checkpoint(path)
    ckpt.update_metadata({"step": step})
    return ckpt


def load_pytree(checkpoint: Checkpoint, target: Any = None) -> Any:
    """Restore a pytree; `target` (abstract or concrete pytree) restores
    sharded/typed to match — required to restore onto a mesh."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        return ckptr.restore(checkpoint.path, target=target)
    return ckptr.restore(checkpoint.path)
